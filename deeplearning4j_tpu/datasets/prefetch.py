"""Double-buffered host->device prefetch (ISSUE 6 piece 3).

:class:`DevicePrefetcher` wraps ANY :class:`DataSetIterator` — the
``AsyncDataSetIterator`` analog pushed one level further down: a
background thread pulls batches from the base iterator, runs an
optional host-side ``prepare`` step (padding, masks), issues
``jax.device_put`` and an optional jitted on-device transform
(e.g. uint8 -> float normalize), and stages up to ``depth`` batches in
a bounded queue. The H2D copy for batch *k+1* (and *k+2*, ...) overlaps
the device compute of batch *k*, so in steady state the trainer's
etl-wait collapses to a queue pop.

Donation safety: every staged batch is a FRESH device buffer produced
by ``device_put`` in the producer thread; the prefetcher never touches
a batch again after handing it to the consumer, so the trainers'
donated-input patterns (and the PR-5 snapshot-clone rule: never hand a
buffer to two owners) hold.

The trainers auto-wrap plain iterators (``MultiLayerNetwork.fit`` and
single-process ``ShardedTrainer.fit``) when ``default_depth() > 0``;
``set_default_depth(0)`` (or ``DL4J_PREFETCH_DEPTH=0``) restores the
blocking path.
"""

from __future__ import annotations

import os
import queue as queue_mod
import threading

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import DataSetIterator

_depth = [max(0, int(os.environ.get("DL4J_PREFETCH_DEPTH", "2")))]


def default_depth() -> int:
    """Prefetch depth trainers use when auto-wrapping iterators
    (0 disables auto-wrap)."""
    return _depth[0]


def set_default_depth(n: int):
    _depth[0] = max(0, int(n))


class DeviceBatch:
    """A training batch staged on device by a trainer-specific
    ``prepare`` callback: features/labels/mask are device arrays, and
    the trainer's fit loop consumes them without the usual host-side
    pad/mask/transfer work. ``bucket`` is the padded batch-axis size
    (``MultiLayerNetwork``'s executable bucket); ``real`` the number of
    non-padding rows (``ShardedTrainer``'s example accounting)."""

    __slots__ = ("features", "labels", "mask", "bucket", "real")

    def __init__(self, features, labels, mask, bucket=None, real=None):
        self.features = features
        self.labels = labels
        self.mask = mask
        self.bucket = bucket
        self.real = real


def _staged_bytes(staged) -> int:
    """Device bytes one staged batch pins (DeviceBatch or DataSet)."""
    from deeplearning4j_tpu.telemetry import memledger

    if isinstance(staged, DeviceBatch):
        return memledger.tree_bytes(
            (staged.features, staged.labels, staged.mask))
    try:
        return memledger.tree_bytes(
            (staged.getFeatures(), staged.getLabels()))
    except Exception:
        return 0


class DevicePrefetcher(DataSetIterator):
    """Background host->device staging around any DataSetIterator.

    - ``depth``: max batches in flight (the double buffer; >=1);
    - ``prepare``: optional ``DataSet -> DataSet | DeviceBatch`` run in
      the producer thread (trainers inject their pad+mask+device_put
      pipelines; default stages features/labels with ``device_put``);
    - ``deviceTransform``: optional jitted ``(features) -> features``
      applied after the transfer — the "normalize/augment-to-float on
      device" hook for uint8 pipelines (``floatOutput=False``).

    Ordering is the base iterator's order (single producer, FIFO
    queue); backpressure is the bounded queue. ``reset()`` restarts the
    producer (draining any stale generation); ``close()`` stops it.
    """

    _END = object()

    def __init__(self, base: DataSetIterator, depth: int | None = None,
                 prepare=None, deviceTransform=None, loop="prefetch"):
        super().__init__(base.batch())
        self._base = base
        self._depth = max(1, depth if depth is not None
                          else (default_depth() or 2))
        self._prepare = prepare
        self._device_transform = deviceTransform
        self._loop = loop
        self._gen = 0
        self._queue = None
        self._thread = None
        self._error = None
        self._done = False
        self._closed = False
        self._tele = None
        self._tele_bound = False
        self._mem_claim = None   # HBM ledger claim for staged batches

    # -- delegation ----------------------------------------------------------
    def getLabels(self):
        return self._base.getLabels()

    def totalOutcomes(self):
        return getattr(self._base, "totalOutcomes", lambda: 0)()

    def set_epoch(self, epoch):
        if hasattr(self._base, "set_epoch"):
            self._base.set_epoch(epoch)

    def __len__(self):
        return len(self._base)

    def resetSupported(self):
        return self._base.resetSupported()

    def setPreProcessor(self, pp):
        # preprocessing belongs to the base (it runs in the producer
        # thread, before staging)
        self._base.setPreProcessor(pp)

    # -- producer ------------------------------------------------------------
    def _instruments(self):
        if not self._tele_bound:
            from deeplearning4j_tpu import telemetry

            self._tele = telemetry.etl_instruments(self._loop)
            self._tele_bound = True
        return self._tele

    def _default_prepare(self, ds):
        import jax

        f = jax.device_put(ds.getFeatures())
        if self._device_transform is not None:
            f = self._device_transform(f)
        labels = ds.getLabels()
        out = DataSet(f, jax.device_put(labels)
                      if labels is not None else None)
        return out

    def _produce(self, gen, q, trace_ctx):
        import time as _time

        from deeplearning4j_tpu.telemetry import memledger, tracing

        prepare = self._prepare or self._default_prepare
        # one flag check per producer generation (the loop_instruments
        # idiom): with telemetry disabled the loop body never computes
        # staged bytes nor touches the ledger
        claim_pending = memledger.enabled()
        try:
            # the consumer's sampled trace context (captured at _start)
            # becomes current on THIS producer thread, so base-iterator
            # work (including the ETL pool's work orders) parents to
            # the training trace across the thread hop (ISSUE 10)
            with tracing.use(trace_ctx):
                self._base.reset()
                while self._gen == gen and self._base.hasNext():
                    item = self._base.next()
                    # no blanket fallback here: trainer prepare
                    # callbacks already return the raw DataSet for
                    # shapes they do not handle, so an exception out of
                    # prepare is a REAL bug (OOM in device_put, bad
                    # deviceTransform) and surfaces at next() via the
                    # error path instead of silently degrading every
                    # batch to the blocking host path
                    t_prep = (_time.perf_counter()
                              if trace_ctx is not None else 0.0)
                    staged = prepare(item)
                    if self._device_transform is not None \
                            and isinstance(staged, DeviceBatch):
                        staged.features = self._device_transform(
                            staged.features)
                    if claim_pending:
                        # HBM ledger (ISSUE 14): up to depth + 1 staged
                        # device batches are pinned by this prefetcher
                        # (depth queued + one in flight) — a capacity
                        # claim stated once per producer generation
                        claim_pending = False
                        self._mem_claim = memledger.claim(
                            "prefetch", self._loop,
                            nbytes=(_staged_bytes(staged)
                                    * (self._depth + 1)),
                            depth=self._depth, basis="depth x batch")
                    if trace_ctx is not None:
                        tracing.emit("prefetch.prepare", trace_ctx,
                                     t_prep, _time.perf_counter(),
                                     loop=self._loop)
                    while self._gen == gen:
                        try:
                            q.put(staged, timeout=0.1)
                            break
                        except queue_mod.Full:
                            continue
        except Exception as e:  # surfaced at next()
            if self._gen == gen:
                # the comment above is load-bearing: an OOM in
                # device_put here IS a real bug — route it through the
                # typed DeviceOomError + flight `oom` forensics
                # (ISSUE 14 satellite) instead of a generic prepare
                # error, so the consumer's next() names the site, the
                # requested bytes, and the top HBM claims
                from deeplearning4j_tpu.telemetry import memledger

                self._error = memledger.oom_error(
                    e, site="prefetch.device_put",
                    loop=self._loop) or e
        finally:
            while self._gen == gen:
                try:
                    q.put(self._END, timeout=0.1)
                    break
                except queue_mod.Full:
                    continue

    def _start(self):
        from deeplearning4j_tpu.telemetry import tracing

        self._gen += 1
        self._queue = queue_mod.Queue(maxsize=self._depth)
        self._error = None
        self._done = False
        self._thread = threading.Thread(
            target=self._produce,
            args=(self._gen, self._queue, tracing.current()),
            daemon=True, name=f"dl4j:prefetch:{self._loop}")
        self._thread.start()

    def _stop(self):
        """Invalidate the current generation and unblock the producer."""
        self._gen += 1
        t, q = self._thread, self._queue
        if t is not None and t.is_alive():
            # drain so a producer blocked on put() sees the stale gen
            while t.is_alive():
                try:
                    q.get(timeout=0.05)
                except queue_mod.Empty:
                    pass
                t.join(timeout=0.05)
        self._thread = None
        self._queue = None
        if self._mem_claim is not None:
            # the staged buffers are dropped with the queue: the claim
            # goes with them (restated by the next producer generation)
            self._mem_claim.release()
            self._mem_claim = None

    # -- consumer ------------------------------------------------------------
    def hasNext(self):
        if getattr(self, "_closed", False):
            return False
        if self._queue is None:
            self._start()
        if self._done:
            return False
        if getattr(self, "_peek", None) is not None:
            return True
        item = self._take()
        if item is None:
            return False
        self._peek = item
        return True

    def next(self):
        if getattr(self, "_closed", False):
            raise StopIteration
        if getattr(self, "_peek", None) is not None:
            item, self._peek = self._peek, None
            return item
        if self._queue is None:
            self._start()
        item = self._take()
        if item is None:
            raise StopIteration
        return item

    def _take(self):
        if self._done:
            return None
        tele = self._instruments()
        try:
            item = self._queue.get_nowait()
            blocked = False
        except queue_mod.Empty:
            item = self._queue.get()
            blocked = True
        if tele is not None and item is not self._END:
            # counted AFTER the pop (no qsize race) and never for the
            # end-of-epoch sentinel: a miss is precisely "the trainer
            # blocked waiting for a real batch"
            (tele.prefetch_misses if blocked
             else tele.prefetch_hits).inc()
            try:
                tele.prefetch_depth.set(self._queue.qsize())
            except NotImplementedError:  # pragma: no cover
                pass
        if item is self._END:
            self._done = True
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            return None
        return item

    def reset(self):
        """Stop the producer; the next consume restarts it. Lazy on
        purpose: training loops reset iterators more than once per
        epoch (`_as_batches` + `__iter__`), and an eagerly restarted
        producer would consume the base iterator's epoch state (epoch
        counters, augmentation seeds) for a generation that is then
        immediately discarded."""
        self._stop()
        self._peek = None

    def close(self):
        """Stop the producer thread; the prefetcher is terminal after
        this (hasNext() False, next() raises — nothing can silently
        respawn a producer over the base iterator). The base iterator
        itself is NOT closed — its lifecycle belongs to the caller."""
        self._stop()
        self._peek = None
        self._done = True
        self._closed = True

    def __del__(self):  # pragma: no cover - best effort
        try:
            self._stop()
        except Exception:
            pass

    # -- multi-batch staging -------------------------------------------------
    def takeMulti(self, k: int):
        """Stack the next ``k`` staged batches into device-resident
        ``[K, batch, ...]`` features/labels for
        ``MultiLayerNetwork.fitMultiBatch`` (the scan-of-K-steps launch
        consumes prefetched input without a host bounce). Returns
        ``(features_k, labels_k)`` or None when fewer than ``k``
        batches remain."""
        import jax.numpy as jnp

        feats, labels = [], []
        for _ in range(k):
            if not self.hasNext():
                return None
            ds = self.next()
            if isinstance(ds, DeviceBatch):
                feats.append(ds.features)
                labels.append(ds.labels)
            else:
                feats.append(ds.getFeatures())
                labels.append(ds.getLabels())
        return jnp.stack(feats), jnp.stack(labels)
