"""DataVec transform engine: Schema + TransformProcess.

Reference capability: `datavec-api` org.datavec.api.transform —
`Schema`/`Schema.Builder` (typed column metadata) and
`TransformProcess`/`TransformProcess.Builder` (a declarative pipeline of
column transforms executed record-by-record), SURVEY.md §2.4 and
VERDICT.md round-1 missing item 2. The reference executes these on
Spark/local executors; here execution is plain host-side Python over
record lists (ETL is host work — the device path starts at the
DataSet), and the output schema is derived eagerly like the reference's
`TransformProcess.getFinalSchema()`.
"""

from __future__ import annotations

import math

import numpy as np

from deeplearning4j_tpu.datasets.records import RecordReader


class ColumnType:
    String = "String"
    Integer = "Integer"
    Long = "Long"
    Double = "Double"
    Float = "Float"
    Categorical = "Categorical"
    Time = "Time"


class Schema:
    """Typed column metadata (reference: org.datavec.api.transform.schema
    .Schema)."""

    def __init__(self, columns):
        # columns: list of (name, type, meta) — meta holds e.g. category
        # state for Categorical columns
        self.columns = list(columns)

    def getColumnNames(self):
        return [c[0] for c in self.columns]

    def getColumnTypes(self):
        return [c[1] for c in self.columns]

    def numColumns(self):
        return len(self.columns)

    def getIndexOfColumn(self, name):
        for i, c in enumerate(self.columns):
            if c[0] == name:
                return i
        raise ValueError(f"no column {name!r} in schema "
                         f"{self.getColumnNames()}")

    def getMetaData(self, name):
        return self.columns[self.getIndexOfColumn(name)][2]

    def __repr__(self):
        cols = ", ".join(f"{n}:{t}" for n, t, _ in self.columns)
        return f"Schema({cols})"

    class Builder:
        def __init__(self):
            self._cols = []

        def addColumnString(self, name):
            self._cols.append((name, ColumnType.String, {}))
            return self

        def addColumnInteger(self, name, minValue=None, maxValue=None):
            self._cols.append((name, ColumnType.Integer,
                               {"min": minValue, "max": maxValue}))
            return self

        def addColumnLong(self, name):
            self._cols.append((name, ColumnType.Long, {}))
            return self

        def addColumnDouble(self, name, minValue=None, maxValue=None):
            self._cols.append((name, ColumnType.Double,
                               {"min": minValue, "max": maxValue}))
            return self

        def addColumnFloat(self, name):
            self._cols.append((name, ColumnType.Float, {}))
            return self

        def addColumnsDouble(self, *names):
            for n in names:
                self.addColumnDouble(n)
            return self

        def addColumnCategorical(self, name, *categories):
            if len(categories) == 1 and isinstance(categories[0],
                                                   (list, tuple)):
                categories = tuple(categories[0])
            self._cols.append((name, ColumnType.Categorical,
                               {"categories": list(categories)}))
            return self

        def build(self) -> "Schema":
            return Schema(self._cols)


# ---------------------------------------------------------------------------
# conditions (reference: org.datavec.api.transform.condition)
# ---------------------------------------------------------------------------

class ConditionOp:
    LessThan = "LessThan"
    LessOrEqual = "LessOrEqual"
    GreaterThan = "GreaterThan"
    GreaterOrEqual = "GreaterOrEqual"
    Equal = "Equal"
    NotEqual = "NotEqual"
    InSet = "InSet"
    NotInSet = "NotInSet"

    _FNS = {
        "LessThan": lambda v, t: v < t,
        "LessOrEqual": lambda v, t: v <= t,
        "GreaterThan": lambda v, t: v > t,
        "GreaterOrEqual": lambda v, t: v >= t,
        "Equal": lambda v, t: v == t,
        "NotEqual": lambda v, t: v != t,
        "InSet": lambda v, t: v in t,
        "NotInSet": lambda v, t: v not in t,
    }


class _SchemaMemo:
    """Per-schema cached computation: rec_fns run once per RECORD, but
    their schema is fixed per step — cache index lookups on schema id
    (schemas live for the TransformProcess lifetime in self._schemas)."""

    def __init__(self, compute):
        self.compute = compute
        self._cache = {}

    def __call__(self, schema):
        # the schema itself is kept in the entry: a bare id() key could
        # alias a new Schema allocated at a freed address
        entry = self._cache.get(id(schema))
        if entry is None or entry[0] is not schema:
            entry = (schema, self.compute(schema))
            self._cache[id(schema)] = entry
        return entry[1]


class _Condition:
    def applies(self, schema, record):
        raise NotImplementedError


class DoubleColumnCondition(_Condition):
    def __init__(self, column, op, value):
        self.column, self.op, self.value = column, op, value
        self._idx = _SchemaMemo(lambda s: s.getIndexOfColumn(self.column))

    def applies(self, schema, record):
        v = float(record[self._idx(schema)])
        return ConditionOp._FNS[self.op](v, self.value)


class CategoricalColumnCondition(_Condition):
    def __init__(self, column, op, value):
        self.column, self.op, self.value = column, op, value
        self._idx = _SchemaMemo(lambda s: s.getIndexOfColumn(self.column))

    def applies(self, schema, record):
        v = str(record[self._idx(schema)])
        return ConditionOp._FNS[self.op](v, self.value)


class StringColumnCondition(CategoricalColumnCondition):
    pass


# ---------------------------------------------------------------------------
# math ops
# ---------------------------------------------------------------------------

class MathOp:
    Add = "Add"
    Subtract = "Subtract"
    Multiply = "Multiply"
    Divide = "Divide"
    Modulus = "Modulus"
    ReverseSubtract = "ReverseSubtract"
    ReverseDivide = "ReverseDivide"
    ScalarMin = "ScalarMin"
    ScalarMax = "ScalarMax"

    _FNS = {
        "Add": lambda v, s: v + s,
        "Subtract": lambda v, s: v - s,
        "Multiply": lambda v, s: v * s,
        "Divide": lambda v, s: v / s,
        "Modulus": lambda v, s: v % s,
        "ReverseSubtract": lambda v, s: s - v,
        "ReverseDivide": lambda v, s: s / v,
        "ScalarMin": lambda v, s: min(v, s),
        "ScalarMax": lambda v, s: max(v, s),
    }


class MathFunction:
    ABS = "ABS"
    CEIL = "CEIL"
    FLOOR = "FLOOR"
    EXP = "EXP"
    LOG = "LOG"
    LOG2 = "LOG2"
    SQRT = "SQRT"
    SIN = "SIN"
    COS = "COS"
    TAN = "TAN"
    SIGNUM = "SIGNUM"

    _FNS = {
        "ABS": abs, "CEIL": math.ceil, "FLOOR": math.floor,
        "EXP": math.exp, "LOG": math.log, "LOG2": math.log2,
        "SQRT": math.sqrt, "SIN": math.sin, "COS": math.cos,
        "TAN": math.tan, "SIGNUM": lambda v: (v > 0) - (v < 0),
    }


# ---------------------------------------------------------------------------
# TransformProcess
# ---------------------------------------------------------------------------

class TransformProcess:
    """A sequence of (schema -> schema, record -> record|None) steps."""

    def __init__(self, initial_schema, steps):
        self.initialSchema = initial_schema
        self.steps = steps  # list of (name, schema_fn, record_fn)
        # derive intermediate schemas eagerly (getFinalSchema parity)
        self._schemas = [initial_schema]
        for _name, schema_fn, _rec in steps:
            self._schemas.append(schema_fn(self._schemas[-1]))

    def getFinalSchema(self) -> Schema:
        return self._schemas[-1]

    def execute(self, records):
        """Transform a list of records; filtered records are dropped."""
        out = []
        for rec in records:
            r = self.executeRecord(rec)
            if r is not None:
                out.append(r)
        return out

    def executeRecord(self, record):
        r = list(record)
        for (name, _schema_fn, rec_fn), schema in zip(self.steps,
                                                      self._schemas):
            r = rec_fn(schema, r)
            if r is None:
                return None
        return r

    class Builder:
        def __init__(self, schema: Schema):
            self.schema = schema
            self.steps = []

        def _add(self, name, schema_fn, rec_fn):
            self.steps.append((name, schema_fn, rec_fn))
            return self

        # -- column removal / selection ---------------------------------

        def removeColumns(self, *names):
            names = set(names)

            def schema_fn(s):
                return Schema([c for c in s.columns if c[0] not in names])

            keep_memo = _SchemaMemo(lambda s: [
                i for i, c in enumerate(s.columns) if c[0] not in names])

            def rec_fn(s, r):
                return [r[i] for i in keep_memo(s)]

            return self._add(f"removeColumns{sorted(names)}", schema_fn,
                             rec_fn)

        def removeAllColumnsExceptFor(self, *names):
            keep_names = set(names)

            def schema_fn(s):
                return Schema([c for c in s.columns if c[0] in keep_names])

            keep_memo = _SchemaMemo(lambda s: [
                i for i, c in enumerate(s.columns) if c[0] in keep_names])

            def rec_fn(s, r):
                return [r[i] for i in keep_memo(s)]

            return self._add("removeAllExcept", schema_fn, rec_fn)

        def reorderColumns(self, *names):
            def schema_fn(s):
                rest = [c for c in s.columns if c[0] not in names]
                picked = [s.columns[s.getIndexOfColumn(n)] for n in names]
                return Schema(picked + rest)

            order_memo = _SchemaMemo(lambda s: (
                lambda idx: idx + [i for i in range(s.numColumns())
                                   if i not in set(idx)])(
                [s.getIndexOfColumn(n) for n in names]))

            def rec_fn(s, r):
                return [r[i] for i in order_memo(s)]

            return self._add("reorder", schema_fn, rec_fn)

        def renameColumn(self, old, new):
            def schema_fn(s):
                return Schema([(new if c[0] == old else c[0], c[1], c[2])
                               for c in s.columns])

            def rec_fn(s, r):
                return r

            return self._add(f"rename {old}->{new}", schema_fn, rec_fn)

        # -- filters -----------------------------------------------------

        def filter(self, condition: _Condition):
            """Drop records MATCHING the condition (reference
            ConditionFilter semantics: removes examples where the
            condition applies)."""

            def schema_fn(s):
                return s

            def rec_fn(s, r):
                return None if condition.applies(s, r) else r

            return self._add("filter", schema_fn, rec_fn)

        # -- categorical -------------------------------------------------

        def categoricalToInteger(self, *names):
            names_set = set(names)

            def schema_fn(s):
                return Schema([
                    (c[0], ColumnType.Integer if c[0] in names_set
                     else c[1], c[2]) for c in s.columns])

            cols_memo = _SchemaMemo(lambda s: [
                (s.getIndexOfColumn(n), s.getMetaData(n)["categories"])
                for n in names_set])

            def rec_fn(s, r):
                out = list(r)
                for i, cats in cols_memo(s):
                    out[i] = cats.index(str(r[i]))
                return out

            return self._add("catToInt", schema_fn, rec_fn)

        def categoricalToOneHot(self, *names):
            def schema_fn(s):
                cols = []
                for c in s.columns:
                    if c[0] in names:
                        for cat in c[2]["categories"]:
                            cols.append((f"{c[0]}[{cat}]",
                                         ColumnType.Integer, {}))
                    else:
                        cols.append(c)
                return Schema(cols)

            def rec_fn(s, r):
                out = []
                for i, c in enumerate(s.columns):
                    if c[0] in names:
                        cats = c[2]["categories"]
                        onehot = [0] * len(cats)
                        onehot[cats.index(str(r[i]))] = 1
                        out.extend(onehot)
                    else:
                        out.append(r[i])
                return out

            return self._add("catToOneHot", schema_fn, rec_fn)

        def integerToOneHot(self, name, minValue, maxValue):
            width = maxValue - minValue + 1

            def schema_fn(s):
                cols = []
                for c in s.columns:
                    if c[0] == name:
                        for v in range(minValue, maxValue + 1):
                            cols.append((f"{name}[{v}]",
                                         ColumnType.Integer, {}))
                    else:
                        cols.append(c)
                return Schema(cols)

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                i = idx_memo(s)
                v = int(r[i])
                if not minValue <= v <= maxValue:
                    raise ValueError(
                        f"integerToOneHot({name!r}): value {v} outside "
                        f"[{minValue}, {maxValue}]")
                onehot = [0] * width
                onehot[v - minValue] = 1
                return list(r[:i]) + onehot + list(r[i + 1:])

            return self._add("intToOneHot", schema_fn, rec_fn)

        def stringToCategorical(self, name, categories):
            cats = list(categories)

            def schema_fn(s):
                return Schema([
                    (c[0], ColumnType.Categorical, {"categories": cats})
                    if c[0] == name else c for c in s.columns])

            def rec_fn(s, r):
                return r

            return self._add("strToCat", schema_fn, rec_fn)

        # -- math --------------------------------------------------------

        def doubleMathOp(self, name, op, scalar):
            def schema_fn(s):
                return s

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                i = idx_memo(s)
                out = list(r)
                out[i] = MathOp._FNS[op](float(r[i]), scalar)
                return out

            return self._add(f"math {op}", schema_fn, rec_fn)

        integerMathOp = doubleMathOp

        def doubleMathFunction(self, name, fn):
            def schema_fn(s):
                return s

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                i = idx_memo(s)
                out = list(r)
                out[i] = MathFunction._FNS[fn](float(r[i]))
                return out

            return self._add(f"mathFn {fn}", schema_fn, rec_fn)

        def normalize(self, name, minValue, maxValue):
            """Min-max scale a column to [0,1] given known bounds."""
            span = maxValue - minValue

            def schema_fn(s):
                return s

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                i = idx_memo(s)
                out = list(r)
                out[i] = (float(r[i]) - minValue) / span
                return out

            return self._add("normalize", schema_fn, rec_fn)

        # -- strings -----------------------------------------------------

        def stringMapTransform(self, name, mapping: dict):
            def schema_fn(s):
                return s

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                i = idx_memo(s)
                out = list(r)
                out[i] = mapping.get(str(r[i]), r[i])
                return out

            return self._add("stringMap", schema_fn, rec_fn)

        def appendStringColumnTransform(self, name, toAppend):
            def schema_fn(s):
                return s

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                i = idx_memo(s)
                out = list(r)
                out[i] = str(r[i]) + toAppend
                return out

            return self._add("appendString", schema_fn, rec_fn)

        def conditionalReplaceValueTransform(self, name, new_value,
                                             condition: _Condition):
            def schema_fn(s):
                return s

            idx_memo = _SchemaMemo(lambda s: s.getIndexOfColumn(name))

            def rec_fn(s, r):
                out = list(r)
                if condition.applies(s, r):
                    out[idx_memo(s)] = new_value
                return out

            return self._add("condReplace", schema_fn, rec_fn)

        def transform(self, name, fn, schema_fn=None):
            """Escape hatch: custom record transform (record -> record)."""

            def sfn(s):
                return schema_fn(s) if schema_fn else s

            return self._add(name, sfn, lambda s, r: fn(s, r))

        def build(self) -> "TransformProcess":
            return TransformProcess(self.schema, self.steps)


class TransformProcessRecordReader(RecordReader):
    """Wrap a RecordReader with a TransformProcess (reference:
    org.datavec.api.records.reader.impl.transform
    .TransformProcessRecordReader). Filtered records are skipped."""

    def __init__(self, recordReader: RecordReader,
                 transformProcess: TransformProcess):
        self.reader = recordReader
        self.tp = transformProcess
        self._pending = None

    def initialize(self, split):
        self.reader.initialize(split)

    def _advance(self):
        while self._pending is None and self.reader.hasNext():
            rec = self.tp.executeRecord(self.reader.next())
            if rec is not None:
                self._pending = rec

    def hasNext(self):
        self._advance()
        return self._pending is not None

    def next(self):
        self._advance()
        if self._pending is None:
            raise StopIteration
        rec, self._pending = self._pending, None
        return rec

    def reset(self):
        self.reader.reset()
        self._pending = None
