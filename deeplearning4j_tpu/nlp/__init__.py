"""NLP layer (reference L7: deeplearning4j-nlp — SURVEY.md §2.7)."""

from deeplearning4j_tpu.nlp.tokenization import (  # noqa: F401
    BasicLineIterator, CollectionSentenceIterator, CommonPreprocessor,
    DefaultTokenizerFactory, SentenceIterator, TokenPreProcess, Tokenizer)
from deeplearning4j_tpu.nlp.word2vec import (  # noqa: F401
    VocabCache, VocabWord, Word2Vec)
from deeplearning4j_tpu.nlp.paragraph_vectors import (  # noqa: F401
    LabelledDocument, ParagraphVectors)
from deeplearning4j_tpu.nlp.serializer import (  # noqa: F401
    WordVectorSerializer)
from deeplearning4j_tpu.nlp.glove import Glove  # noqa: F401
