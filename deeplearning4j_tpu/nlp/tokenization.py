"""Tokenization + sentence iteration.

Reference capability: deeplearning4j-nlp's TokenizerFactory
(DefaultTokenizerFactory + preprocessors) and SentenceIterator impls
(BasicLineIterator, CollectionSentenceIterator) — SURVEY.md §2.7 NLP.
Host-side text processing, as in the reference."""

from __future__ import annotations

import re


class TokenPreProcess:
    def preProcess(self, token: str) -> str:
        return token


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation (reference: CommonPreprocessor)."""

    _PUNCT = re.compile(r"[\d.:,\"'()\[\]|/?!;]+")

    def preProcess(self, token):
        return self._PUNCT.sub("", token.lower())


class Tokenizer:
    def __init__(self, tokens):
        self._tokens = tokens

    def getTokens(self):
        return list(self._tokens)

    def countTokens(self):
        return len(self._tokens)


class DefaultTokenizerFactory:
    def __init__(self):
        self._pre: TokenPreProcess | None = None

    def setTokenPreProcessor(self, pre: TokenPreProcess):
        self._pre = pre

    def create(self, text: str) -> Tokenizer:
        toks = text.split()
        if self._pre is not None:
            toks = [self._pre.preProcess(t) for t in toks]
        return Tokenizer([t for t in toks if t])


class SentenceIterator:
    def __iter__(self):
        self.reset()
        return self._iter()

    def _iter(self):
        while self.hasNext():
            yield self.nextSentence()

    def hasNext(self):
        raise NotImplementedError

    def nextSentence(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences):
        self._sentences = list(sentences)
        self._pos = 0

    def hasNext(self):
        return self._pos < len(self._sentences)

    def nextSentence(self):
        s = self._sentences[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference: BasicLineIterator)."""

    def __init__(self, path):
        self.path = path
        self._lines = None
        self._pos = 0

    def _ensure(self):
        if self._lines is None:
            with open(self.path) as f:
                self._lines = [line.strip() for line in f if line.strip()]

    def hasNext(self):
        self._ensure()
        return self._pos < len(self._lines)

    def nextSentence(self):
        self._ensure()
        s = self._lines[self._pos]
        self._pos += 1
        return s

    def reset(self):
        self._ensure()
        self._pos = 0
