"""Word2Vec: SkipGram / CBOW with negative sampling, device-resident.

Reference capability: deeplearning4j-nlp org.deeplearning4j.models.word2vec
.Word2Vec + SkipGram/CBOW learning algorithms (BASELINE.json configs[4],
SURVEY.md §2.7). The reference's hot loop is a host-driven sparse custom op
(libnd4j `skipgram`) per word pair; here training is BATCHED on device
(SURVEY.md §7 hard part 6): one jitted step takes [B] centers, [B]
contexts, [B,K] negatives, and jax.grad's gather VJP produces exactly the
sparse scatter-add update the reference hand-codes — fused with the SGD
apply, params donated.

Vocab build, frequent-word subsampling, window pairing, and unigram^0.75
negative-table sampling are host-side numpy (they are ETL, not math)."""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, SentenceIterator)


class VocabWord:
    def __init__(self, word, count, index):
        self.word = word
        self.count = count
        self.index = index


class VocabCache:
    def __init__(self):
        self.words: list[VocabWord] = []
        self._by_word: dict[str, VocabWord] = {}

    def add(self, word, count):
        vw = VocabWord(word, count, len(self.words))
        self.words.append(vw)
        self._by_word[word] = vw
        return vw

    def containsWord(self, w):
        return w in self._by_word

    def indexOf(self, w):
        return self._by_word[w].index if w in self._by_word else -1

    def wordAtIndex(self, i):
        return self.words[i].word

    def wordFrequency(self, w):
        return self._by_word[w].count if w in self._by_word else 0

    def numWords(self):
        return len(self.words)

    def totalWordOccurrences(self):
        return sum(w.count for w in self.words)


def _sgns_loss(syn0, syn1, centers, contexts, negatives, weights):
    """Skip-gram negative sampling loss for a batch.
    centers [B], contexts [B], negatives [B,K], weights [B] (0 = padding)."""
    c = syn0[centers]                      # [B,D]
    pos = syn1[contexts]                   # [B,D]
    neg = syn1[negatives]                  # [B,K,D]
    pos_score = jnp.sum(c * pos, axis=-1)
    neg_score = jnp.einsum("bd,bkd->bk", c, neg)
    # -log sigma(pos) - sum log sigma(-neg), numerically stable.
    # SUM over the batch (not mean): each pair must contribute a full
    # per-pair SGD update like the reference's sequential loop — a mean
    # would divide the learning rate by the batch size. Weights zero out
    # tail-padding pairs exactly (sum, so no denominator to bias).
    per_pair = (jax.nn.softplus(-pos_score)
                + jnp.sum(jax.nn.softplus(neg_score), axis=-1))
    return jnp.sum(per_pair * weights)


def _cbow_loss(syn0, syn1, contexts_mat, context_mask, centers, negatives,
               weights):
    """CBOW: mean of context word vectors predicts the center.
    contexts_mat [B,W], context_mask [B,W], centers [B], negatives [B,K],
    weights [B] (0 = padding)."""
    ctx = syn0[contexts_mat]               # [B,W,D]
    m = context_mask[..., None]
    mean = jnp.sum(ctx * m, axis=1) / jnp.maximum(
        jnp.sum(m, axis=1), 1.0)           # [B,D]
    pos = syn1[centers]
    neg = syn1[negatives]
    pos_score = jnp.sum(mean * pos, axis=-1)
    neg_score = jnp.einsum("bd,bkd->bk", mean, neg)
    per_pair = (jax.nn.softplus(-pos_score)
                + jnp.sum(jax.nn.softplus(neg_score), axis=-1))
    return jnp.sum(per_pair * weights)


def _compaction_dests(val_s, cap):
    """Stream-compaction scatter destinations for `cap` slots with
    validity mask `val_s`: valid slot -> its rank among valid slots
    (cumsum-1), invalid slot -> a DISTINCT out-of-range dest (cap +
    slot index). Every dest is unique across the whole array — the
    downstream scatters promise unique_indices=True, and a shared
    sentinel dest would be UB per the JAX scatter docs even though
    mode="drop" discards those writes (ADVICE r4). Returns
    (dests, n_valid) — the count rides the cumsum already computed."""
    csum = jnp.cumsum(val_s.astype(jnp.int32))
    return jnp.where(val_s, csum - 1,
                     cap + jnp.arange(cap, dtype=jnp.int32)), csum[-1]


class Word2Vec:
    class Builder:
        def __init__(self):
            # batchSize 8192: r4's probe_sgns measured step throughput
            # rising 1.5 -> 4.3 Mpairs/s from 2048 -> 8192 (per-step
            # fixed costs amortize); SGNS quality is batch-tolerant
            # (hogwild heritage) and the pair order is shuffled
            self._kw = dict(minWordFrequency=5, layerSize=100, windowSize=5,
                            negative=5, learningRate=0.025, epochs=1,
                            iterations=1, seed=42, batchSize=8192,
                            sampling=1e-3, algorithm="skipgram")
            self._iter = None
            self._tok = None

        def minWordFrequency(self, n):
            self._kw["minWordFrequency"] = n
            return self

        def layerSize(self, n):
            self._kw["layerSize"] = n
            return self

        def windowSize(self, n):
            self._kw["windowSize"] = n
            return self

        def negativeSampling(self, n):
            self._kw["negative"] = int(n)
            return self

        def negative(self, n):
            return self.negativeSampling(n)

        def negativeSample(self, n):
            # DL4J name: Word2Vec.Builder#negativeSample(double)
            return self.negativeSampling(n)

        def learningRate(self, lr):
            self._kw["learningRate"] = lr
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def iterations(self, n):
            self._kw["iterations"] = n
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def batchSize(self, n):
            self._kw["batchSize"] = n
            return self

        def deviceETL(self, b=True):
            """Generate skip-gram pairs on the accelerator (default ON
            for the SGNS path): host uploads only the subsampled corpus.
            Turn off to use the host/native pair generator (needed for
            shufflePairs)."""
            self._kw["deviceETL"] = bool(b)
            return self

        def shufflePairs(self, b=True):
            """Globally shuffle the epoch's (center, context) pairs
            before batching. The reference trains in corpus order, so
            this defaults OFF; turn on to decorrelate batches at ~3 s
            host cost per 10M words."""
            self._kw["shufflePairs"] = bool(b)
            return self

        def sampling(self, s):
            self._kw["sampling"] = s
            return self

        def exactNegatives(self, b=True):
            """Draw fresh negatives for every pair inside every step
            (the r4 semantics). Default OFF: negatives come from a
            per-launch pool of iid unigram^0.75 draws, each step
            slicing a pseudo-random window — 0.65 ms/step cheaper on
            the tunnel-attached chip (tools/probe_w2v_step.py), same
            marginal distribution, but pool windows can overlap across
            steps."""
            self._kw["exactNegatives"] = bool(b)
            return self

        def elementsLearningAlgorithm(self, name):
            self._kw["algorithm"] = ("cbow" if "cbow" in str(name).lower()
                                     else "skipgram")
            return self

        def iterate(self, sentence_iterator: SentenceIterator):
            self._iter = sentence_iterator
            return self

        def tokenizerFactory(self, tok):
            self._tok = tok
            return self

        def build(self) -> "Word2Vec":
            return Word2Vec(self._iter, self._tok or
                            DefaultTokenizerFactory(), **self._kw)

    def __init__(self, sentence_iterator, tokenizer_factory, **kw):
        self.sentences = sentence_iterator
        self.tokenizer = tokenizer_factory
        self.cfg = kw
        self.vocab = VocabCache()
        self.syn0 = None     # input vectors [V,D]
        self.syn1 = None     # output vectors [V,D]
        self._neg_table = None
        self._neg_table_int = None
        self._step_fn = None
        self._multi_fn = None
        self._k_bucket = None

    # -- vocab ---------------------------------------------------------------
    def _invalidate_corpus_caches(self):
        """Drop every token/corpus/pairgen cache derived from the current
        sentences+vocab (ADVICE r5: the caches were never invalidated, so
        refitting after a corpus or vocab change silently trained on the
        stale uploaded corpus). Called by buildVocab(); call directly
        after mutating `sentences` in place without rebuilding the
        vocab."""
        for attr in ("_tok_flat", "_tok_offsets", "_keep_prob",
                     "_corpus_dev", "_keep_prob_dev", "_pairgen_fn",
                     "_neg_table_dev", "_fused_fn", "_fused_sig"):
            if hasattr(self, attr):
                delattr(self, attr)
        # K-bucket / step fns are shape-keyed: a new corpus/vocab means
        # new pair counts and possibly a new vocab size, so let them
        # rebuild rather than reuse a stale bucket
        self._k_bucket = None
        self._step_fn = None
        self._multi_fn = None

    def buildVocab(self):
        self._invalidate_corpus_caches()
        old_words = [w.word for w in self.vocab.words]
        self.vocab = VocabCache()
        counts: dict[str, int] = {}
        for sent in self.sentences:
            for t in self.tokenizer.create(sent).getTokens():
                counts[t] = counts.get(t, 0) + 1
        min_f = self.cfg["minWordFrequency"]
        for w, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if c >= min_f:
                self.vocab.add(w, c)
        if self.vocab.numWords() == 0:
            raise ValueError(
                f"empty vocab: no word reaches minWordFrequency={min_f}")
        if self.syn0 is not None and \
                [w.word for w in self.vocab.words] != old_words:
            # the word -> index mapping changed (size OR order OR
            # membership): trained vectors no longer line up with
            # indices — restart rather than silently misassign
            self.syn0 = None
            self.syn1 = None
        self._build_neg_tables()
        return self

    def _build_neg_tables(self):
        """Unigram^0.75 negative-sampling tables from the current vocab —
        callable lazily too, for models whose vocab was installed by a
        deserializer rather than buildVocab()."""
        freqs = np.array([max(w.count, 1) for w in self.vocab.words],
                         np.float64)
        probs = freqs ** 0.75
        self._neg_table = (probs / probs.sum()).astype(np.float64)
        # quantized unigram table (the original word2vec trick): sampling
        # becomes a uniform-int gather, ~10x cheaper than choice(p=...)
        table_size = min(1_000_000, max(10_000, 100 * len(freqs)))
        counts = np.maximum(
            1, np.round(self._neg_table * table_size)).astype(np.int64)
        self._neg_table_int = np.repeat(
            np.arange(len(freqs), dtype=np.int32), counts)

    # -- pair generation (host ETL) -----------------------------------------
    def _encode_corpus(self, rng):
        total = self.vocab.totalWordOccurrences()
        t = self.cfg["sampling"]
        encoded = []
        for sent in self.sentences:
            idxs = []
            for tok in self.tokenizer.create(sent).getTokens():
                i = self.vocab.indexOf(tok)
                if i < 0:
                    continue
                if t > 0:
                    f = self.vocab.words[i].count / total
                    keep = (math.sqrt(f / t) + 1) * (t / f) if f > t else 1.0
                    if rng.random() > keep:
                        continue
                idxs.append(i)
            if len(idxs) > 1:
                encoded.append(np.asarray(idxs, np.int32))
        return encoded

    def _flat_token_cache(self):
        """One-time tokenize+index of the whole corpus into a flat int32
        array + sentence offsets, so per-epoch subsampling is a vectorized
        numpy pass instead of a 10M-iteration Python loop (VERDICT
        round-2 item 5: at >=10M words the old per-token loop was the
        bottleneck, not the chip)."""
        if getattr(self, "_tok_flat", None) is not None:
            return self._tok_flat, self._tok_offsets, self._keep_prob
        by_word = self.vocab._by_word
        flats, lens = [], []
        for sent in self.sentences:
            toks = self.tokenizer.create(sent).getTokens()
            idx = [by_word[t].index for t in toks if t in by_word]
            flats.append(np.asarray(idx, np.int32))
            lens.append(len(idx))
        self._tok_flat = (np.concatenate(flats) if flats
                          else np.zeros(0, np.int32))
        self._tok_offsets = np.zeros(len(lens) + 1, np.int64)
        np.cumsum(lens, out=self._tok_offsets[1:])
        t = self.cfg["sampling"]
        if t > 0:
            total = self.vocab.totalWordOccurrences()
            f = np.array([w.count / total for w in self.vocab.words],
                         np.float64)
            keep = np.where(f > t, (np.sqrt(f / t) + 1) * (t / f), 1.0)
            self._keep_prob = np.minimum(keep, 1.0).astype(np.float32)
        else:
            self._keep_prob = None
        return self._tok_flat, self._tok_offsets, self._keep_prob

    def _subsampled_flat(self, rng):
        """Per-epoch frequent-word subsampling, vectorized over the flat
        token array. Returns (flat, offsets)."""
        flat, offsets, keep_prob = self._flat_token_cache()
        if keep_prob is None:
            return flat, offsets
        mask = rng.random(len(flat)) < keep_prob[flat]
        kept = flat[mask]
        # per-sentence kept counts via prefix sums — exact for empty
        # sentences anywhere, including a trailing all-OOV/blank one
        # (np.add.reduceat would index out of bounds there)
        csum = np.zeros(len(flat) + 1, np.int64)
        np.cumsum(mask, out=csum[1:])
        new_offsets = csum[offsets]
        return kept.astype(np.int32), new_offsets

    # -- device-side pair generation (r4, reworked r5) ----------------------
    def _build_pairgen(self, subsample: bool):
        """Jitted per-epoch ETL entirely ON DEVICE: frequent-word
        subsampling (bernoulli keep + stream compaction of the token
        stream), then skip-gram pair generation + pair compaction. The
        host uploads the tokenized corpus ONCE across all epochs; the
        r4 design re-uploaded the host-subsampled corpus every epoch
        and spent ~3.5 s/epoch of a 10M-word fit in host numpy +
        tunnel transfer (r5 phase instrumentation).

        Semantics match the host pair-gen: subsample-then-window (the
        window closes over removed tokens), per-position window radius
        b ~ U[1, W], contexts pos+d for 0 < |d| <= b within the same
        sentence, pairs emitted in corpus order (position-major, d
        ascending). Compaction is cumsum + unique-index scatter; the
        invalid slots' scatter targets fall off the end and are
        dropped."""
        w = self.cfg["windowSize"]

        def shift(a, d):
            """a[clip(pos+d, 0, p-1)] as slice+concat: TPU scalar
            gathers measured ~0.19 GB/s on this chip where slices run
            at full bandwidth — the r4 gather formulation spent ~3.4 s
            of the 4.4 s pair-gen in 10 shifted gathers
            (tools/probe_w2v_pairgen.py, r5)."""
            p = a.shape[0]
            if d > 0:
                return jnp.concatenate(
                    [a[d:], jnp.broadcast_to(a[-1:], (d,))])
            return jnp.concatenate(
                [jnp.broadcast_to(a[:1], (-d,)), a[:d]])

        def gen(flat, sid, keep_prob, key_sub, key_b):
            p = flat.shape[0]
            if subsample:
                u = jax.random.uniform(key_sub, (p,))
                keep = (sid >= 0) & (u < keep_prob[flat])
                dest, _nk = _compaction_dests(keep, p)
                flat = jnp.zeros((p,), jnp.int32).at[dest].set(
                    flat, mode="drop", unique_indices=True)
                sid = jnp.full((p,), -1, jnp.int32).at[dest].set(
                    sid, mode="drop", unique_indices=True)
            pos = jnp.arange(p, dtype=jnp.int32)
            b = jax.random.randint(key_b, (p,), 1, w + 1)
            cents, ctxs, vals = [], [], []
            for d in (*range(-w, 0), *range(1, w + 1)):
                valid = ((sid >= 0) & (shift(sid, d) == sid)
                         & (jnp.abs(d) <= b)
                         & (pos + d >= 0) & (pos + d < p))
                cents.append(flat)
                ctxs.append(shift(flat, d))
                vals.append(valid)
            cent_s = jnp.stack(cents, 1).reshape(-1)
            ctx_s = jnp.stack(ctxs, 1).reshape(-1)
            val_s = jnp.stack(vals, 1).reshape(-1)
            cap = cent_s.shape[0]
            dest, n_real = _compaction_dests(val_s, cap)
            # (a packed-slot single-scatter + gather-decode variant
            # measured SLOWER than these two element scatters — r4; a
            # [cap, 2] row-scatter variant measured 4x slower still,
            # and scatter-free searchsorted compaction 10x slower —
            # tools/probe_w2v_pairgen.py, r5)
            out_c = jnp.zeros((cap,), jnp.int32).at[dest].set(
                cent_s, mode="drop", unique_indices=True)
            out_x = jnp.zeros((cap,), jnp.int32).at[dest].set(
                ctx_s, mode="drop", unique_indices=True)
            return out_c, out_x, n_real

        return jax.jit(gen)

    def _device_pairs(self, rng):
        """Generate + compact the epoch's pairs on device (subsampling
        included). Returns (cent_dev, ctx_dev, n_real) with cent/ctx
        length = the padded slot capacity (first n_real are real)."""
        flat, offsets, keep_prob = self._flat_token_cache()
        if getattr(self, "_corpus_dev", None) is None:
            sid = np.repeat(
                np.arange(len(offsets) - 1, dtype=np.int32),
                np.diff(offsets))
            p_b = -(-max(1, len(flat)) // 1024) * 1024
            flat_pad = np.zeros(p_b, np.int32)
            flat_pad[:len(flat)] = flat
            sid_pad = np.full(p_b, -1, np.int32)
            sid_pad[:len(flat)] = sid
            self._corpus_dev = (jax.device_put(flat_pad),
                                jax.device_put(sid_pad))
            self._keep_prob_dev = (
                jax.device_put(keep_prob) if keep_prob is not None
                else jnp.zeros((1,), jnp.float32))
        if getattr(self, "_pairgen_fn", None) is None:
            self._pairgen_fn = self._build_pairgen(keep_prob is not None)
        key_sub = jax.random.key(int(rng.integers(0, 2 ** 31)))
        key_b = jax.random.key(int(rng.integers(0, 2 ** 31)), impl="rbg")
        cent, ctx, n = self._pairgen_fn(
            *self._corpus_dev, self._keep_prob_dev, key_sub, key_b)
        return cent, ctx, int(n)

    def _make_pairs_flat(self, flat, offsets, rng):
        """Skip-gram pairs straight from (flat, offsets) — native kernel
        when available, list-based fallback otherwise."""
        win = self.cfg["windowSize"]
        bs_all = rng.integers(1, win + 1, len(flat)).astype(np.int32)

        from deeplearning4j_tpu import native

        if native.available():
            pairs = native.sg_pairs_flat(flat, offsets, bs_all)
            if pairs is not None:
                return pairs
        centers, contexts = [], []
        for i in range(len(offsets) - 1):
            idxs = flat[offsets[i]:offsets[i + 1]]
            bs = bs_all[offsets[i]:offsets[i + 1]]
            n = len(idxs)
            for pos in range(n):
                b = bs[pos]
                lo, hi = max(0, pos - b), min(n, pos + b + 1)
                for j in range(lo, hi):
                    if j != pos:
                        centers.append(idxs[pos])
                        contexts.append(idxs[j])
        return (np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def _make_pairs(self, encoded, rng):
        """List-of-sentences front end over _make_pairs_flat (kept for
        the CBOW path and API compatibility)."""
        if not encoded:
            return (np.zeros(0, np.int32), np.zeros(0, np.int32))
        flat = np.concatenate(encoded).astype(np.int32)
        offsets = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(s) for s in encoded], out=offsets[1:])
        return self._make_pairs_flat(flat, offsets, rng)

    # -- training ------------------------------------------------------------
    def _build_step(self, cbow):
        lr = self.cfg["learningRate"]
        loss_fn = _cbow_loss if cbow else _sgns_loss

        def step(syn0, syn1, *batch):
            loss, (g0, g1) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(syn0, syn1, *batch)
            return loss, syn0 - lr * g0, syn1 - lr * g1

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_multi_step_fused(self, k, bsz, n_pool):
        """Whole-epoch SGNS training in ONE device launch: lax.scan over
        the epoch's [K, bsz] batches, sliced+reshaped from the pair-gen
        output INSIDE the jit (r5: the separate pad/reshape/weights
        prep launches were ~0.4 s/epoch of tunnel round-trips).

        Negatives come from a per-launch POOL: one vectorized
        randint+table-gather of n_pool draws, with each step taking a
        pseudo-random contiguous slice. The r4 per-step fold_in +
        randint + gather cost 0.65 ms of the 1.9 ms step
        (tools/probe_w2v_step.py G variant) — a dynamic slice is free,
        and each slice is still iid unigram^0.75 draws independent of
        the step's pairs (windows may overlap across steps; set
        exactNegatives(True) for per-step draws). Step losses are not
        computed (nothing consumed them; the analytic gradients don't
        need the loss value)."""
        lr = self.cfg["learningRate"]
        k_neg = self.cfg["negative"]
        full = k * bsz

        def many_fused(syn0, syn1, cent_all, ctx_all, n_real, table,
                       key):
            tsize = table.shape[0]
            d = syn0.shape[1]
            cent_k = cent_all[:full].reshape(k, bsz)
            ctx_k = ctx_all[:full].reshape(k, bsz)
            w_k = (jnp.arange(full, dtype=jnp.int32) < n_real) \
                .astype(jnp.float32).reshape(k, bsz)
            draws = jax.random.randint(key, (n_pool,), 0, tsize)
            pool = table[draws]
            span = bsz * k_neg

            def body(carry, xs):
                syn0, syn1, i = carry
                cent, ctx, w = xs
                off = (i.astype(jnp.uint32) * jnp.uint32(2654435761)
                       % jnp.uint32(n_pool - span)).astype(jnp.int32)
                negs = jax.lax.dynamic_slice(
                    pool, (off,), (span,)).reshape(bsz, k_neg)
                c = syn0[cent]
                pos = syn1[ctx]
                neg = syn1[negs]
                pos_s = jnp.sum(c * pos, axis=-1)
                neg_s = jnp.einsum("bd,bkd->bk", c, neg)
                dpos = -(1.0 - jax.nn.sigmoid(pos_s)) * w
                dneg = jax.nn.sigmoid(neg_s) * w[:, None]
                gc = dpos[:, None] * pos + \
                    jnp.einsum("bk,bkd->bd", dneg, neg)
                o0 = jnp.argsort(cent)
                syn0 = syn0.at[cent[o0]].add(
                    -lr * gc[o0], indices_are_sorted=True)
                ids1 = jnp.concatenate([ctx, negs.reshape(-1)])
                u1 = jnp.concatenate([
                    dpos[:, None] * c,
                    (dneg[..., None] * c[:, None, :]).reshape(-1, d)])
                o1 = jnp.argsort(ids1)
                syn1 = syn1.at[ids1[o1]].add(
                    -lr * u1[o1], indices_are_sorted=True)
                return (syn0, syn1, i + 1), None

            (syn0, syn1, _), _ = jax.lax.scan(
                body, (syn0, syn1, jnp.int32(0)), (cent_k, ctx_k, w_k))
            return syn0, syn1

        return jax.jit(many_fused, donate_argnums=(0, 1),
                       static_argnames=())

    def _build_multi_step(self):
        """Pre-r5 scan over host-prepared [K, bsz] batches with exact
        per-step negative draws (exactNegatives(True) / shufflePairs
        path)."""
        lr = self.cfg["learningRate"]
        k_neg = self.cfg["negative"]

        def many(syn0, syn1, cent_k, ctx_k, w_k, table, key):
            tsize = table.shape[0]
            d = syn0.shape[1]

            def body(carry, xs):
                syn0, syn1, i = carry
                cent, ctx, w = xs
                draws = jax.random.randint(
                    jax.random.fold_in(key, i),
                    (cent.shape[0], k_neg), 0, tsize)
                negs = table[draws]
                # Analytic SGNS gradients + SORTED row scatters instead
                # of jax.grad: the grad-of-gather path materializes a
                # DENSE [V,D] gradient table per step (plus a dense
                # axpy), which r4's probe_sgns measured as the real
                # bound — the sorted in-place row update is ~3x faster
                # at the same math (sort cost ~2% of step;
                # indices_are_sorted lets XLA's scatter skip the
                # unsorted-duplicate slow path, probe_scatter r4:
                # 125M vs 78M rows/s).
                c = syn0[cent]
                pos = syn1[ctx]
                neg = syn1[negs]
                pos_s = jnp.sum(c * pos, axis=-1)
                neg_s = jnp.einsum("bd,bkd->bk", c, neg)
                loss = jnp.sum(
                    (jax.nn.softplus(-pos_s)
                     + jnp.sum(jax.nn.softplus(neg_s), axis=-1)) * w)
                dpos = -(1.0 - jax.nn.sigmoid(pos_s)) * w      # [B]
                dneg = jax.nn.sigmoid(neg_s) * w[:, None]      # [B,K]
                gc = dpos[:, None] * pos + \
                    jnp.einsum("bk,bkd->bd", dneg, neg)
                o0 = jnp.argsort(cent)
                syn0 = syn0.at[cent[o0]].add(
                    -lr * gc[o0], indices_are_sorted=True)
                ids1 = jnp.concatenate([ctx, negs.reshape(-1)])
                u1 = jnp.concatenate([
                    dpos[:, None] * c,
                    (dneg[..., None] * c[:, None, :]).reshape(-1, d)])
                o1 = jnp.argsort(ids1)
                syn1 = syn1.at[ids1[o1]].add(
                    -lr * u1[o1], indices_are_sorted=True)
                return (syn0, syn1, i + 1), loss

            (syn0, syn1, _), losses = jax.lax.scan(
                body, (syn0, syn1, jnp.int32(0)), (cent_k, ctx_k, w_k))
            return losses, syn0, syn1

        return jax.jit(many, donate_argnums=(0, 1))

    def fit(self):
        if self.vocab.numWords() == 0:
            self.buildVocab()
        if self._neg_table_int is None:
            # vocab may have been installed by a deserializer
            self._build_neg_tables()
        cfg = self.cfg
        v, d = self.vocab.numWords(), cfg["layerSize"]
        rng = np.random.default_rng(cfg["seed"])
        key = jax.random.key(cfg["seed"])
        if self.syn0 is None:
            self.syn0 = (jax.random.uniform(key, (v, d), jnp.float32)
                         - 0.5) / d
            self.syn1 = jnp.zeros((v, d), jnp.float32)
        cbow = cfg["algorithm"] == "cbow"
        if self._step_fn is None:
            self._step_fn = self._build_step(cbow)
        k_neg = cfg["negative"]
        bsz = cfg["batchSize"]
        syn0, syn1 = self.syn0, self.syn1
        if not cbow and getattr(self, "_neg_table_dev", None) is None:
            self._neg_table_dev = jax.device_put(
                jnp.asarray(self._neg_table_int))
        for _epoch in range(cfg["epochs"]):
            if not cbow:
                # SGNS fast path: vectorized subsampling over the cached
                # flat token array, native pair-gen, then the epoch's
                # batches stacked into one scan launch per `iterations`
                # pass with on-device negative draws
                device_etl = (self.cfg.get("deviceETL", True)
                              and not self.cfg.get("shufflePairs"))
                if device_etl:
                    # upload the ~30 MB corpus, generate pairs on chip
                    cent_all, ctx_all, n = self._device_pairs(rng)
                else:
                    flat, offsets = self._subsampled_flat(rng)
                    centers, contexts = self._make_pairs_flat(
                        flat, offsets, rng)
                    if self.cfg.get("shufflePairs"):
                        # the reference trains in corpus order; opt-in
                        # shuffle costs ~3 s/epoch per 10M words on host
                        order = rng.permutation(len(centers))
                        centers = centers[order]
                        contexts = contexts[order]
                    n = len(centers)
                k = max(1, (n + bsz - 1) // bsz)
                # bucket K with a 2% margin (and to a multiple of 8) so
                # subsampling-induced pair-count jitter across epochs
                # reuses ONE compiled scan — a bare multiple-of-8 bucket
                # left ~0.2% headroom, so a later epoch could exceed it
                # and silently RECOMPILE the whole-epoch scan (~12 s)
                # inside fit (r4 bench diagnosis); extra batches are
                # zero-weighted
                k = -(-(k + max(8, k // 50)) // 8) * 8
                if self._k_bucket is None or k > self._k_bucket:
                    self._k_bucket = k
                k = self._k_bucket
                full = k * bsz
                if device_etl and not self.cfg.get("exactNegatives"):
                    # fused path: slice/reshape/weights + pooled
                    # negatives inside ONE launch
                    if full > cent_all.shape[0]:
                        cent_all = jnp.pad(
                            cent_all, (0, full - cent_all.shape[0]))
                        ctx_all = jnp.pad(
                            ctx_all, (0, full - ctx_all.shape[0]))
                    pool = max(1 << 21, 2 * bsz * k_neg)
                    if getattr(self, "_fused_fn", None) is None or \
                            self._fused_sig != (k, bsz):
                        self._fused_fn = self._build_multi_step_fused(
                            k, bsz, pool)
                        self._fused_sig = (k, bsz)
                    for it in range(cfg["iterations"]):
                        key = jax.random.key(
                            int(rng.integers(0, 2**31)))
                        syn0, syn1 = self._fused_fn(
                            syn0, syn1, cent_all, ctx_all,
                            jnp.int32(n), self._neg_table_dev, key)
                    continue
                if device_etl:
                    # first n slots are real pairs; the tail (and any
                    # slice beyond the compacted region) is zero-weighted
                    pad = full - cent_all.shape[0]
                    if pad > 0:
                        cent_all = jnp.pad(cent_all, (0, pad))
                        ctx_all = jnp.pad(ctx_all, (0, pad))
                    cent_k = cent_all[:full].reshape(k, bsz)
                    ctx_k = ctx_all[:full].reshape(k, bsz)
                    w_k = (jnp.arange(full, dtype=jnp.int32) < n) \
                        .astype(jnp.float32).reshape(k, bsz)
                else:
                    w_flat = np.concatenate(
                        [np.ones(n, np.float32),
                         np.zeros(full - n, np.float32)])
                    # device_put explicitly: numpy args to a jitted call
                    # take a slow synchronous per-argument transfer path
                    # over the tunnel (r4 measurement)
                    cent_k = jax.device_put(
                        np.resize(centers, full).reshape(k, bsz))
                    ctx_k = jax.device_put(
                        np.resize(contexts, full).reshape(k, bsz))
                    w_k = jax.device_put(w_flat.reshape(k, bsz))
                if getattr(self, "_multi_fn", None) is None:
                    self._multi_fn = self._build_multi_step()
                for it in range(cfg["iterations"]):
                    # threefry, not rbg: the per-step fold_in+randint
                    # inside the scan measured 0.26 ms/step cheaper
                    # (1.55 vs 1.81 ms, tools/probe_w2v_step.py F
                    # variants, r5) — rbg's fold_in is the slow part
                    key = jax.random.key(int(rng.integers(0, 2**31)))
                    _losses, syn0, syn1 = self._multi_fn(
                        syn0, syn1, cent_k, ctx_k, w_k,
                        self._neg_table_dev, key)
                continue
            encoded = self._encode_corpus(rng)
            batches = self._cbow_batches(encoded, rng, bsz)
            for _ in range(cfg["iterations"]):
                for batch in batches:
                    b = len(batch[0])
                    if b == 0:
                        continue
                    # pad the tail batch to the full batch size with
                    # zero-weighted pairs: ONE compiled shape regardless of
                    # how the stochastic subsampling changes the pair count
                    # across epochs (the loss is a weighted SUM, so the
                    # padding contributes exactly zero loss and gradient)
                    full = max(bsz, b)
                    pad = full - b
                    weights = np.concatenate(
                        [np.ones(b, np.float32), np.zeros(pad, np.float32)])
                    batch = tuple(
                        np.concatenate(
                            [a, np.zeros((pad,) + a.shape[1:], a.dtype)])
                        if pad else a for a in batch)
                    negs = rng.choice(v, size=(full, k_neg),
                                      p=self._neg_table).astype(np.int32)
                    if cbow:
                        ctx_mat, mask, cent = batch
                        loss, syn0, syn1 = self._step_fn(
                            syn0, syn1, ctx_mat, mask, cent, negs, weights)
                    else:
                        cent, ctx = batch
                        loss, syn0, syn1 = self._step_fn(
                            syn0, syn1, cent, ctx, negs, weights)
        self.syn0, self.syn1 = syn0, syn1
        return self

    def _cbow_batches(self, encoded, rng, bsz):
        win = self.cfg["windowSize"]
        rows_ctx, rows_mask, rows_center = [], [], []
        width = 2 * win
        for idxs in encoded:
            n = len(idxs)
            bs = rng.integers(1, win + 1, n)
            for pos in range(n):
                b = bs[pos]
                lo, hi = max(0, pos - b), min(n, pos + b + 1)
                ctx = [idxs[j] for j in range(lo, hi) if j != pos]
                if not ctx:
                    continue
                row = np.zeros(width, np.int32)
                msk = np.zeros(width, np.float32)
                row[:len(ctx)] = ctx
                msk[:len(ctx)] = 1.0
                rows_ctx.append(row)
                rows_mask.append(msk)
                rows_center.append(idxs[pos])
        ctx_m = np.stack(rows_ctx)
        mask = np.stack(rows_mask)
        cent = np.asarray(rows_center, np.int32)
        order = np.random.default_rng(0).permutation(len(cent))
        ctx_m, mask, cent = ctx_m[order], mask[order], cent[order]
        out = [(ctx_m[i:i + bsz], mask[i:i + bsz], cent[i:i + bsz])
               for i in range(0, len(cent), bsz)]
        return out or [(ctx_m, mask, cent)]

    # -- lookups -------------------------------------------------------------
    def getWordVector(self, word) -> np.ndarray:
        i = self.vocab.indexOf(word)
        if i < 0:
            raise KeyError(word)
        return np.asarray(self.syn0[i])

    def getWordVectorMatrix(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def hasWord(self, w):
        return self.vocab.containsWord(w)

    def similarity(self, a, b) -> float:
        va, vb = self.getWordVector(a), self.getWordVector(b)
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb)
                                + 1e-12))

    def wordsNearest(self, word_or_vec, n=10) -> list:
        if isinstance(word_or_vec, str):
            vec = self.getWordVector(word_or_vec)
            exclude = {word_or_vec}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        m = self.getWordVectorMatrix()
        norms = np.linalg.norm(m, axis=1) * (np.linalg.norm(vec) + 1e-12)
        sims = m @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.wordAtIndex(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= n:
                break
        return out
