"""Word2Vec: SkipGram / CBOW with negative sampling, device-resident.

Reference capability: deeplearning4j-nlp org.deeplearning4j.models.word2vec
.Word2Vec + SkipGram/CBOW learning algorithms (BASELINE.json configs[4],
SURVEY.md §2.7). The reference's hot loop is a host-driven sparse custom op
(libnd4j `skipgram`) per word pair; here training is BATCHED on device
(SURVEY.md §7 hard part 6): one jitted step takes [B] centers, [B]
contexts, [B,K] negatives, and jax.grad's gather VJP produces exactly the
sparse scatter-add update the reference hand-codes — fused with the SGD
apply, params donated.

Vocab build, frequent-word subsampling, window pairing, and unigram^0.75
negative-table sampling are host-side numpy (they are ETL, not math)."""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp.tokenization import (
    DefaultTokenizerFactory, SentenceIterator)


class VocabWord:
    def __init__(self, word, count, index):
        self.word = word
        self.count = count
        self.index = index


class VocabCache:
    def __init__(self):
        self.words: list[VocabWord] = []
        self._by_word: dict[str, VocabWord] = {}

    def add(self, word, count):
        vw = VocabWord(word, count, len(self.words))
        self.words.append(vw)
        self._by_word[word] = vw
        return vw

    def containsWord(self, w):
        return w in self._by_word

    def indexOf(self, w):
        return self._by_word[w].index if w in self._by_word else -1

    def wordAtIndex(self, i):
        return self.words[i].word

    def wordFrequency(self, w):
        return self._by_word[w].count if w in self._by_word else 0

    def numWords(self):
        return len(self.words)

    def totalWordOccurrences(self):
        return sum(w.count for w in self.words)


def _sgns_loss(syn0, syn1, centers, contexts, negatives, weights):
    """Skip-gram negative sampling loss for a batch.
    centers [B], contexts [B], negatives [B,K], weights [B] (0 = padding)."""
    c = syn0[centers]                      # [B,D]
    pos = syn1[contexts]                   # [B,D]
    neg = syn1[negatives]                  # [B,K,D]
    pos_score = jnp.sum(c * pos, axis=-1)
    neg_score = jnp.einsum("bd,bkd->bk", c, neg)
    # -log sigma(pos) - sum log sigma(-neg), numerically stable.
    # SUM over the batch (not mean): each pair must contribute a full
    # per-pair SGD update like the reference's sequential loop — a mean
    # would divide the learning rate by the batch size. Weights zero out
    # tail-padding pairs exactly (sum, so no denominator to bias).
    per_pair = (jax.nn.softplus(-pos_score)
                + jnp.sum(jax.nn.softplus(neg_score), axis=-1))
    return jnp.sum(per_pair * weights)


def _cbow_loss(syn0, syn1, contexts_mat, context_mask, centers, negatives,
               weights):
    """CBOW: mean of context word vectors predicts the center.
    contexts_mat [B,W], context_mask [B,W], centers [B], negatives [B,K],
    weights [B] (0 = padding)."""
    ctx = syn0[contexts_mat]               # [B,W,D]
    m = context_mask[..., None]
    mean = jnp.sum(ctx * m, axis=1) / jnp.maximum(
        jnp.sum(m, axis=1), 1.0)           # [B,D]
    pos = syn1[centers]
    neg = syn1[negatives]
    pos_score = jnp.sum(mean * pos, axis=-1)
    neg_score = jnp.einsum("bd,bkd->bk", mean, neg)
    per_pair = (jax.nn.softplus(-pos_score)
                + jnp.sum(jax.nn.softplus(neg_score), axis=-1))
    return jnp.sum(per_pair * weights)


class Word2Vec:
    class Builder:
        def __init__(self):
            self._kw = dict(minWordFrequency=5, layerSize=100, windowSize=5,
                            negative=5, learningRate=0.025, epochs=1,
                            iterations=1, seed=42, batchSize=2048,
                            sampling=1e-3, algorithm="skipgram")
            self._iter = None
            self._tok = None

        def minWordFrequency(self, n):
            self._kw["minWordFrequency"] = n
            return self

        def layerSize(self, n):
            self._kw["layerSize"] = n
            return self

        def windowSize(self, n):
            self._kw["windowSize"] = n
            return self

        def negativeSampling(self, n):
            self._kw["negative"] = int(n)
            return self

        def negative(self, n):
            return self.negativeSampling(n)

        def negativeSample(self, n):
            # DL4J name: Word2Vec.Builder#negativeSample(double)
            return self.negativeSampling(n)

        def learningRate(self, lr):
            self._kw["learningRate"] = lr
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def iterations(self, n):
            self._kw["iterations"] = n
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def batchSize(self, n):
            self._kw["batchSize"] = n
            return self

        def sampling(self, s):
            self._kw["sampling"] = s
            return self

        def elementsLearningAlgorithm(self, name):
            self._kw["algorithm"] = ("cbow" if "cbow" in str(name).lower()
                                     else "skipgram")
            return self

        def iterate(self, sentence_iterator: SentenceIterator):
            self._iter = sentence_iterator
            return self

        def tokenizerFactory(self, tok):
            self._tok = tok
            return self

        def build(self) -> "Word2Vec":
            return Word2Vec(self._iter, self._tok or
                            DefaultTokenizerFactory(), **self._kw)

    def __init__(self, sentence_iterator, tokenizer_factory, **kw):
        self.sentences = sentence_iterator
        self.tokenizer = tokenizer_factory
        self.cfg = kw
        self.vocab = VocabCache()
        self.syn0 = None     # input vectors [V,D]
        self.syn1 = None     # output vectors [V,D]
        self._neg_table = None
        self._neg_table_int = None
        self._step_fn = None
        self._multi_fn = None
        self._k_bucket = None

    # -- vocab ---------------------------------------------------------------
    def buildVocab(self):
        counts: dict[str, int] = {}
        for sent in self.sentences:
            for t in self.tokenizer.create(sent).getTokens():
                counts[t] = counts.get(t, 0) + 1
        min_f = self.cfg["minWordFrequency"]
        for w, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if c >= min_f:
                self.vocab.add(w, c)
        if self.vocab.numWords() == 0:
            raise ValueError(
                f"empty vocab: no word reaches minWordFrequency={min_f}")
        self._build_neg_tables()
        return self

    def _build_neg_tables(self):
        """Unigram^0.75 negative-sampling tables from the current vocab —
        callable lazily too, for models whose vocab was installed by a
        deserializer rather than buildVocab()."""
        freqs = np.array([max(w.count, 1) for w in self.vocab.words],
                         np.float64)
        probs = freqs ** 0.75
        self._neg_table = (probs / probs.sum()).astype(np.float64)
        # quantized unigram table (the original word2vec trick): sampling
        # becomes a uniform-int gather, ~10x cheaper than choice(p=...)
        table_size = min(1_000_000, max(10_000, 100 * len(freqs)))
        counts = np.maximum(
            1, np.round(self._neg_table * table_size)).astype(np.int64)
        self._neg_table_int = np.repeat(
            np.arange(len(freqs), dtype=np.int32), counts)

    # -- pair generation (host ETL) -----------------------------------------
    def _encode_corpus(self, rng):
        total = self.vocab.totalWordOccurrences()
        t = self.cfg["sampling"]
        encoded = []
        for sent in self.sentences:
            idxs = []
            for tok in self.tokenizer.create(sent).getTokens():
                i = self.vocab.indexOf(tok)
                if i < 0:
                    continue
                if t > 0:
                    f = self.vocab.words[i].count / total
                    keep = (math.sqrt(f / t) + 1) * (t / f) if f > t else 1.0
                    if rng.random() > keep:
                        continue
                idxs.append(i)
            if len(idxs) > 1:
                encoded.append(np.asarray(idxs, np.int32))
        return encoded

    def _flat_token_cache(self):
        """One-time tokenize+index of the whole corpus into a flat int32
        array + sentence offsets, so per-epoch subsampling is a vectorized
        numpy pass instead of a 10M-iteration Python loop (VERDICT
        round-2 item 5: at >=10M words the old per-token loop was the
        bottleneck, not the chip)."""
        if getattr(self, "_tok_flat", None) is not None:
            return self._tok_flat, self._tok_offsets, self._keep_prob
        by_word = self.vocab._by_word
        flats, lens = [], []
        for sent in self.sentences:
            toks = self.tokenizer.create(sent).getTokens()
            idx = [by_word[t].index for t in toks if t in by_word]
            flats.append(np.asarray(idx, np.int32))
            lens.append(len(idx))
        self._tok_flat = (np.concatenate(flats) if flats
                          else np.zeros(0, np.int32))
        self._tok_offsets = np.zeros(len(lens) + 1, np.int64)
        np.cumsum(lens, out=self._tok_offsets[1:])
        t = self.cfg["sampling"]
        if t > 0:
            total = self.vocab.totalWordOccurrences()
            f = np.array([w.count / total for w in self.vocab.words],
                         np.float64)
            keep = np.where(f > t, (np.sqrt(f / t) + 1) * (t / f), 1.0)
            self._keep_prob = np.minimum(keep, 1.0).astype(np.float32)
        else:
            self._keep_prob = None
        return self._tok_flat, self._tok_offsets, self._keep_prob

    def _subsampled_flat(self, rng):
        """Per-epoch frequent-word subsampling, vectorized over the flat
        token array. Returns (flat, offsets)."""
        flat, offsets, keep_prob = self._flat_token_cache()
        if keep_prob is None:
            return flat, offsets
        mask = rng.random(len(flat)) < keep_prob[flat]
        kept = flat[mask]
        # per-sentence kept counts via prefix sums — exact for empty
        # sentences anywhere, including a trailing all-OOV/blank one
        # (np.add.reduceat would index out of bounds there)
        csum = np.zeros(len(flat) + 1, np.int64)
        np.cumsum(mask, out=csum[1:])
        new_offsets = csum[offsets]
        return kept.astype(np.int32), new_offsets

    def _make_pairs_flat(self, flat, offsets, rng):
        """Skip-gram pairs straight from (flat, offsets) — native kernel
        when available, list-based fallback otherwise."""
        win = self.cfg["windowSize"]
        bs_all = rng.integers(1, win + 1, len(flat)).astype(np.int32)

        from deeplearning4j_tpu import native

        if native.available():
            pairs = native.sg_pairs_flat(flat, offsets, bs_all)
            if pairs is not None:
                return pairs
        centers, contexts = [], []
        for i in range(len(offsets) - 1):
            idxs = flat[offsets[i]:offsets[i + 1]]
            bs = bs_all[offsets[i]:offsets[i + 1]]
            n = len(idxs)
            for pos in range(n):
                b = bs[pos]
                lo, hi = max(0, pos - b), min(n, pos + b + 1)
                for j in range(lo, hi):
                    if j != pos:
                        centers.append(idxs[pos])
                        contexts.append(idxs[j])
        return (np.asarray(centers, np.int32),
                np.asarray(contexts, np.int32))

    def _make_pairs(self, encoded, rng):
        """List-of-sentences front end over _make_pairs_flat (kept for
        the CBOW path and API compatibility)."""
        if not encoded:
            return (np.zeros(0, np.int32), np.zeros(0, np.int32))
        flat = np.concatenate(encoded).astype(np.int32)
        offsets = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(s) for s in encoded], out=offsets[1:])
        return self._make_pairs_flat(flat, offsets, rng)

    # -- training ------------------------------------------------------------
    def _build_step(self, cbow):
        lr = self.cfg["learningRate"]
        loss_fn = _cbow_loss if cbow else _sgns_loss

        def step(syn0, syn1, *batch):
            loss, (g0, g1) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(syn0, syn1, *batch)
            return loss, syn0 - lr * g0, syn1 - lr * g1

        return jax.jit(step, donate_argnums=(0, 1))

    def _build_multi_step(self):
        """Whole-epoch SGNS training in ONE device launch: lax.scan over
        stacked [K, bsz] batches (same dispatch-amortization as
        MultiLayerNetwork.fitMultiBatch — per-launch RPC latency exceeds
        a whole SGNS step at default batch sizes). Negative draws happen
        ON DEVICE inside the scan (uniform ints into the quantized
        unigram table) — at 10M-word scale the host-drawn [K, bsz, k_neg]
        tensor alone is ~1 GB/epoch of host RNG + upload."""
        lr = self.cfg["learningRate"]
        k_neg = self.cfg["negative"]

        def many(syn0, syn1, cent_k, ctx_k, w_k, table, key):
            tsize = table.shape[0]

            def body(carry, xs):
                syn0, syn1, i = carry
                cent, ctx, w = xs
                draws = jax.random.randint(
                    jax.random.fold_in(key, i),
                    (cent.shape[0], k_neg), 0, tsize)
                negs = table[draws]
                loss, (g0, g1) = jax.value_and_grad(
                    _sgns_loss, argnums=(0, 1))(syn0, syn1, cent, ctx,
                                                negs, w)
                return (syn0 - lr * g0, syn1 - lr * g1, i + 1), loss

            (syn0, syn1, _), losses = jax.lax.scan(
                body, (syn0, syn1, jnp.int32(0)), (cent_k, ctx_k, w_k))
            return losses, syn0, syn1

        return jax.jit(many, donate_argnums=(0, 1))

    def fit(self):
        if self.vocab.numWords() == 0:
            self.buildVocab()
        if self._neg_table_int is None:
            # vocab may have been installed by a deserializer
            self._build_neg_tables()
        cfg = self.cfg
        v, d = self.vocab.numWords(), cfg["layerSize"]
        rng = np.random.default_rng(cfg["seed"])
        key = jax.random.key(cfg["seed"])
        if self.syn0 is None:
            self.syn0 = (jax.random.uniform(key, (v, d), jnp.float32)
                         - 0.5) / d
            self.syn1 = jnp.zeros((v, d), jnp.float32)
        cbow = cfg["algorithm"] == "cbow"
        if self._step_fn is None:
            self._step_fn = self._build_step(cbow)
        k_neg = cfg["negative"]
        bsz = cfg["batchSize"]
        syn0, syn1 = self.syn0, self.syn1
        if not cbow and getattr(self, "_neg_table_dev", None) is None:
            self._neg_table_dev = jax.device_put(
                jnp.asarray(self._neg_table_int))
        for _epoch in range(cfg["epochs"]):
            if not cbow:
                # SGNS fast path: vectorized subsampling over the cached
                # flat token array, native pair-gen, then the epoch's
                # batches stacked into one scan launch per `iterations`
                # pass with on-device negative draws
                flat, offsets = self._subsampled_flat(rng)
                centers, contexts = self._make_pairs_flat(flat, offsets,
                                                          rng)
                order = rng.permutation(len(centers))
                centers, contexts = centers[order], contexts[order]
                n = len(centers)
                k = max(1, (n + bsz - 1) // bsz)
                # bucket K (rounded up to a multiple of 8) so subsampling-
                # induced pair-count jitter across epochs reuses ONE
                # compiled scan (extra batches are zero-weighted)
                k = -(-k // 8) * 8
                if self._k_bucket is None or k > self._k_bucket:
                    self._k_bucket = k
                k = self._k_bucket
                full = k * bsz
                w_flat = np.concatenate(
                    [np.ones(n, np.float32),
                     np.zeros(full - n, np.float32)])
                cent_k = np.resize(centers, full).reshape(k, bsz)
                ctx_k = np.resize(contexts, full).reshape(k, bsz)
                w_k = w_flat.reshape(k, bsz)
                if getattr(self, "_multi_fn", None) is None:
                    self._multi_fn = self._build_multi_step()
                for it in range(cfg["iterations"]):
                    key = jax.random.key(
                        int(rng.integers(0, 2**31)), impl="rbg")
                    _losses, syn0, syn1 = self._multi_fn(
                        syn0, syn1, cent_k, ctx_k, w_k,
                        self._neg_table_dev, key)
                continue
            encoded = self._encode_corpus(rng)
            batches = self._cbow_batches(encoded, rng, bsz)
            for _ in range(cfg["iterations"]):
                for batch in batches:
                    b = len(batch[0])
                    if b == 0:
                        continue
                    # pad the tail batch to the full batch size with
                    # zero-weighted pairs: ONE compiled shape regardless of
                    # how the stochastic subsampling changes the pair count
                    # across epochs (the loss is a weighted SUM, so the
                    # padding contributes exactly zero loss and gradient)
                    full = max(bsz, b)
                    pad = full - b
                    weights = np.concatenate(
                        [np.ones(b, np.float32), np.zeros(pad, np.float32)])
                    batch = tuple(
                        np.concatenate(
                            [a, np.zeros((pad,) + a.shape[1:], a.dtype)])
                        if pad else a for a in batch)
                    negs = rng.choice(v, size=(full, k_neg),
                                      p=self._neg_table).astype(np.int32)
                    if cbow:
                        ctx_mat, mask, cent = batch
                        loss, syn0, syn1 = self._step_fn(
                            syn0, syn1, ctx_mat, mask, cent, negs, weights)
                    else:
                        cent, ctx = batch
                        loss, syn0, syn1 = self._step_fn(
                            syn0, syn1, cent, ctx, negs, weights)
        self.syn0, self.syn1 = syn0, syn1
        return self

    def _cbow_batches(self, encoded, rng, bsz):
        win = self.cfg["windowSize"]
        rows_ctx, rows_mask, rows_center = [], [], []
        width = 2 * win
        for idxs in encoded:
            n = len(idxs)
            bs = rng.integers(1, win + 1, n)
            for pos in range(n):
                b = bs[pos]
                lo, hi = max(0, pos - b), min(n, pos + b + 1)
                ctx = [idxs[j] for j in range(lo, hi) if j != pos]
                if not ctx:
                    continue
                row = np.zeros(width, np.int32)
                msk = np.zeros(width, np.float32)
                row[:len(ctx)] = ctx
                msk[:len(ctx)] = 1.0
                rows_ctx.append(row)
                rows_mask.append(msk)
                rows_center.append(idxs[pos])
        ctx_m = np.stack(rows_ctx)
        mask = np.stack(rows_mask)
        cent = np.asarray(rows_center, np.int32)
        order = np.random.default_rng(0).permutation(len(cent))
        ctx_m, mask, cent = ctx_m[order], mask[order], cent[order]
        out = [(ctx_m[i:i + bsz], mask[i:i + bsz], cent[i:i + bsz])
               for i in range(0, len(cent), bsz)]
        return out or [(ctx_m, mask, cent)]

    # -- lookups -------------------------------------------------------------
    def getWordVector(self, word) -> np.ndarray:
        i = self.vocab.indexOf(word)
        if i < 0:
            raise KeyError(word)
        return np.asarray(self.syn0[i])

    def getWordVectorMatrix(self) -> np.ndarray:
        return np.asarray(self.syn0)

    def hasWord(self, w):
        return self.vocab.containsWord(w)

    def similarity(self, a, b) -> float:
        va, vb = self.getWordVector(a), self.getWordVector(b)
        return float(va @ vb / (np.linalg.norm(va) * np.linalg.norm(vb)
                                + 1e-12))

    def wordsNearest(self, word_or_vec, n=10) -> list:
        if isinstance(word_or_vec, str):
            vec = self.getWordVector(word_or_vec)
            exclude = {word_or_vec}
        else:
            vec = np.asarray(word_or_vec)
            exclude = set()
        m = self.getWordVectorMatrix()
        norms = np.linalg.norm(m, axis=1) * (np.linalg.norm(vec) + 1e-12)
        sims = m @ vec / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.wordAtIndex(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= n:
                break
        return out
