"""GloVe word embeddings.

Reference capability: `deeplearning4j-nlp` org.deeplearning4j.models.glove
.Glove (SURVEY.md §2.7 NLP row): co-occurrence-count factorization with
the weighted least-squares objective

    J = sum_ij f(X_ij) (w_i . w~_j + b_i + b~_j - log X_ij)^2,
    f(x) = min(1, (x / xMax)^alpha)

The reference accumulates a co-occurrence map on worker threads and
updates vectors with per-parameter AdaGrad; here the co-occurrence pass
is host ETL (dict accumulation, 1/distance weighting like the
reference's windowed iteration) and ALL nonzero cells train as shuffled
device-resident batches through one jitted donated AdaGrad step —
gather/scatter-add on the embedding tables, the same MXU/VPU pattern as
the Word2Vec trainer (word2vec.py)."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.word2vec import VocabCache, Word2Vec


def _glove_loss(params, rows, cols, logx, weight):
    w, wt, b, bt = params["w"], params["wt"], params["b"], params["bt"]
    dots = jnp.sum(w[rows] * wt[cols], axis=-1)
    diff = dots + b[rows] + bt[cols] - logx
    return jnp.sum(weight * diff * diff)


class Glove:
    class Builder:
        def __init__(self):
            self._kw = {
                "minWordFrequency": 1, "vectorLength": 100,
                "windowSize": 5, "xMax": 100.0, "alpha": 0.75,
                "learningRate": 0.05, "epochs": 5, "batchSize": 4096,
                "seed": 0, "symmetric": True, "shuffle": True,
            }
            self._iter = None
            self._tok = None

        def minWordFrequency(self, n):
            self._kw["minWordFrequency"] = int(n)
            return self

        def vectorLength(self, n):
            self._kw["vectorLength"] = int(n)
            return self

        # DL4J name alias
        layerSize = vectorLength

        def windowSize(self, n):
            self._kw["windowSize"] = int(n)
            return self

        def xMax(self, x):
            self._kw["xMax"] = float(x)
            return self

        def alpha(self, a):
            self._kw["alpha"] = float(a)
            return self

        def learningRate(self, lr):
            self._kw["learningRate"] = float(lr)
            return self

        def epochs(self, n):
            self._kw["epochs"] = int(n)
            return self

        def batchSize(self, n):
            self._kw["batchSize"] = int(n)
            return self

        def seed(self, s):
            self._kw["seed"] = int(s)
            return self

        def symmetric(self, b):
            self._kw["symmetric"] = bool(b)
            return self

        def shuffle(self, b):
            self._kw["shuffle"] = bool(b)
            return self

        def iterate(self, sentence_iterator):
            self._iter = sentence_iterator
            return self

        def tokenizerFactory(self, tok):
            self._tok = tok
            return self

        def build(self) -> "Glove":
            return Glove(self._iter, self._tok or
                         DefaultTokenizerFactory(), **self._kw)

    def __init__(self, sentence_iterator, tokenizer_factory, **kw):
        self.sentences = sentence_iterator
        self.tokenizer = tokenizer_factory
        self.cfg = kw
        self.vocab = VocabCache()
        self.params = None
        self._step_fn = None

    # -- vocab + co-occurrence (host ETL) -----------------------------------
    def buildVocab(self):
        counts: dict[str, int] = {}
        for sent in self.sentences:
            for t in self.tokenizer.create(sent).getTokens():
                counts[t] = counts.get(t, 0) + 1
        min_f = self.cfg["minWordFrequency"]
        for w, c in sorted(counts.items(), key=lambda kv: (-kv[1], kv[0])):
            if c >= min_f:
                self.vocab.add(w, c)
        if self.vocab.numWords() == 0:
            raise ValueError(
                f"empty vocab: no word reaches minWordFrequency={min_f}")
        return self

    def _cooccurrences(self):
        """{(i, j): weighted count} with 1/distance weighting (the
        reference's CoOccurrences pass)."""
        win = self.cfg["windowSize"]
        sym = self.cfg["symmetric"]
        co: dict[tuple, float] = {}
        for sent in self.sentences:
            idxs = [self.vocab.indexOf(t)
                    for t in self.tokenizer.create(sent).getTokens()]
            idxs = [i for i in idxs if i >= 0]
            for pos, i in enumerate(idxs):
                for off in range(1, win + 1):
                    j_pos = pos + off
                    if j_pos >= len(idxs):
                        break
                    j = idxs[j_pos]
                    wgt = 1.0 / off
                    co[(i, j)] = co.get((i, j), 0.0) + wgt
                    if sym:
                        co[(j, i)] = co.get((j, i), 0.0) + wgt
        return co

    # -- device training -----------------------------------------------------
    def _build_step(self):
        lr = self.cfg["learningRate"]

        def step(params, grads_sq, rows, cols, logx, weight):
            loss, g = jax.value_and_grad(_glove_loss)(
                params, rows, cols, logx, weight)
            new_p, new_gsq = {}, {}
            for k in params:
                gsq = grads_sq[k] + g[k] * g[k]
                new_p[k] = params[k] - lr * g[k] / jnp.sqrt(gsq + 1e-8)
                new_gsq[k] = gsq
            return loss, new_p, new_gsq

        return jax.jit(step, donate_argnums=(0, 1))

    def fit(self):
        if self.vocab.numWords() == 0:
            self.buildVocab()
        cfg = self.cfg
        v, d = self.vocab.numWords(), cfg["vectorLength"]
        rng = np.random.default_rng(cfg["seed"])
        key = jax.random.key(cfg["seed"])
        if self.params is None:
            k1, k2 = jax.random.split(key)
            init = lambda k: (jax.random.uniform(  # noqa: E731
                k, (v, d), jnp.float32) - 0.5) / d
            self.params = {"w": init(k1), "wt": init(k2),
                           "b": jnp.zeros((v,)), "bt": jnp.zeros((v,))}
        grads_sq = jax.tree_util.tree_map(jnp.zeros_like, self.params)
        if self._step_fn is None:
            self._step_fn = self._build_step()

        co = self._cooccurrences()
        if not co:
            raise ValueError("no co-occurrences (corpus too small?)")
        pairs = np.asarray(list(co.keys()), np.int32)
        counts = np.asarray(list(co.values()), np.float32)
        logx = np.log(counts)
        weight = np.minimum(
            1.0, (counts / cfg["xMax"]) ** cfg["alpha"]).astype(np.float32)
        bsz = min(cfg["batchSize"], len(pairs))

        losses = []
        for _epoch in range(cfg["epochs"]):
            order = (rng.permutation(len(pairs)) if cfg["shuffle"]
                     else np.arange(len(pairs)))
            total = 0.0
            for s in range(0, len(order) - bsz + 1, bsz):
                sel = order[s:s + bsz]
                loss, self.params, grads_sq = self._step_fn(
                    self.params, grads_sq, pairs[sel, 0], pairs[sel, 1],
                    logx[sel], weight[sel])
                total += float(loss)
            tail = order[len(order) - (len(order) % bsz):]
            if len(tail):
                # pad the ragged tail with zero-weight entries (stable
                # jit signature, no recompile)
                pad = bsz - len(tail)
                sel = np.concatenate([tail, tail[:1].repeat(pad)])
                wpad = weight[sel].copy()
                wpad[len(tail):] = 0.0
                loss, self.params, grads_sq = self._step_fn(
                    self.params, grads_sq, pairs[sel, 0], pairs[sel, 1],
                    logx[sel], wpad)
                total += float(loss)
            losses.append(total / max(len(pairs), 1))
        self._loss_curve = losses
        return self

    # -- lookups (same surface as Word2Vec) ----------------------------------
    def getWordVectorMatrix(self) -> np.ndarray:
        # the published GloVe convention: w + w~ as the final embedding
        return np.asarray(self.params["w"]) + np.asarray(self.params["wt"])

    def getWordVector(self, word) -> np.ndarray:
        i = self.vocab.indexOf(word)
        if i < 0:
            raise KeyError(word)
        return self.getWordVectorMatrix()[i]

    def hasWord(self, w):
        return self.vocab.containsWord(w)

    similarity = Word2Vec.similarity
    wordsNearest = Word2Vec.wordsNearest
