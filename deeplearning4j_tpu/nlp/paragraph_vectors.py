"""ParagraphVectors (doc2vec).

Reference capability: org.deeplearning4j.models.paragraphvectors
.ParagraphVectors (SURVEY.md §2.7) — PV-DBOW: a document vector predicts
the words it contains (skip-gram with the doc id as the 'center');
inferVector() runs gradient steps on a fresh doc vector with word vectors
frozen. Same batched device step as Word2Vec."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _sgns_loss


class LabelledDocument:
    def __init__(self, content, label):
        self.content = content
        self.label = label


class ParagraphVectors(Word2Vec):
    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._docs = None

        def iterate(self, docs):
            """docs: list of LabelledDocument or (label, text) tuples."""
            self._docs = [
                d if isinstance(d, LabelledDocument)
                else LabelledDocument(d[1], d[0]) for d in docs
            ]
            return self

        def build(self) -> "ParagraphVectors":
            from deeplearning4j_tpu.nlp.tokenization import (
                CollectionSentenceIterator)

            sentences = CollectionSentenceIterator(
                [d.content for d in self._docs])
            pv = ParagraphVectors(sentences,
                                  self._tok or DefaultTokenizerFactory(),
                                  **self._kw)
            pv.docs = self._docs
            return pv

    def __init__(self, sentence_iterator, tokenizer_factory, **kw):
        super().__init__(sentence_iterator, tokenizer_factory, **kw)
        self.docs: list[LabelledDocument] = []
        self.doc_vecs = None
        self._labels: list[str] = []
        self._doc_step = None

    def fit(self):
        self.buildVocab() if self.vocab.numWords() == 0 else None
        cfg = self.cfg
        rng = np.random.default_rng(cfg["seed"])
        key = jax.random.key(cfg["seed"] + 1)
        v, d = self.vocab.numWords(), cfg["layerSize"]
        n_docs = len(self.docs)
        self._labels = [doc.label for doc in self.docs]
        if self.syn0 is None:
            self.syn0 = (jax.random.uniform(key, (v, d), jnp.float32)
                         - 0.5) / d
            self.syn1 = jnp.zeros((v, d), jnp.float32)
        if self.doc_vecs is None:
            self.doc_vecs = (jax.random.uniform(
                jax.random.fold_in(key, 1), (n_docs, d), jnp.float32)
                - 0.5) / d
        lr = cfg["learningRate"]
        k_neg = cfg["negative"]

        def step(doc_vecs, syn1, doc_ids, words, negs, weights):
            loss, (gd, g1) = jax.value_and_grad(
                _sgns_loss, argnums=(0, 1))(doc_vecs, syn1, doc_ids, words,
                                            negs, weights)
            return loss, doc_vecs - lr * gd, syn1 - lr * g1

        step = jax.jit(step, donate_argnums=(0, 1))
        doc_vecs, syn1 = self.doc_vecs, self.syn1
        bsz = cfg["batchSize"]
        for _epoch in range(cfg["epochs"]):
            doc_ids, words = [], []
            for di, doc in enumerate(self.docs):
                for tok in self.tokenizer.create(doc.content).getTokens():
                    wi = self.vocab.indexOf(tok)
                    if wi >= 0:
                        doc_ids.append(di)
                        words.append(wi)
            doc_ids = np.asarray(doc_ids, np.int32)
            words = np.asarray(words, np.int32)
            order = rng.permutation(len(doc_ids))
            doc_ids, words = doc_ids[order], words[order]
            for i in range(0, len(doc_ids), bsz):
                dids = doc_ids[i:i + bsz]
                ws = words[i:i + bsz]
                b = len(dids)
                if b == 0:
                    continue
                # zero-weight-pad the tail to one stable compiled shape
                full = max(bsz, b)
                pad = full - b
                weights = np.concatenate(
                    [np.ones(b, np.float32), np.zeros(pad, np.float32)])
                if pad:
                    dids = np.concatenate([dids, np.zeros(pad, np.int32)])
                    ws = np.concatenate([ws, np.zeros(pad, np.int32)])
                negs = rng.choice(v, size=(full, k_neg),
                                  p=self._neg_table).astype(np.int32)
                loss, doc_vecs, syn1 = step(doc_vecs, syn1, dids, ws, negs,
                                            weights)
        self.doc_vecs, self.syn1 = doc_vecs, syn1
        return self

    def getVector(self, label) -> np.ndarray:
        return np.asarray(self.doc_vecs[self._labels.index(label)])

    def inferVector(self, text, steps=20) -> np.ndarray:
        """Fit a fresh doc vector against frozen word output vectors."""
        cfg = self.cfg
        rng = np.random.default_rng(0)
        words = [self.vocab.indexOf(t)
                 for t in self.tokenizer.create(text).getTokens()]
        words = np.asarray([w for w in words if w >= 0], np.int32)
        if len(words) == 0:
            return np.zeros(cfg["layerSize"], np.float32)
        vec = (rng.random(cfg["layerSize"]).astype(np.float32) - 0.5) \
            / cfg["layerSize"]
        vec = jnp.asarray(vec)
        syn1 = self.syn1
        lr = cfg["learningRate"]

        @jax.jit
        def istep(vec, words, negs):
            def loss_fn(v):
                dv = jnp.broadcast_to(v, (len(words), v.shape[0]))
                pos = syn1[words]
                neg = syn1[negs]
                p = jnp.sum(dv * pos, axis=-1)
                ns = jnp.einsum("bd,bkd->bk", dv, neg)
                # mean here: inferVector fits ONE vector, so per-word sum
                # would scale the step with document length
                return jnp.mean(jax.nn.softplus(-p)
                                + jnp.sum(jax.nn.softplus(ns), axis=-1))

            g = jax.grad(loss_fn)(vec)
            return vec - lr * g

        for _ in range(steps):
            negs = rng.choice(self.vocab.numWords(),
                              size=(len(words), cfg["negative"]),
                              p=self._neg_table).astype(np.int32)
            vec = istep(vec, words, negs)
        return np.asarray(vec)

    def similarityToLabel(self, text, label) -> float:
        a = self.inferVector(text)
        b = self.getVector(label)
        return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))

    def nearestLabels(self, text, n=5) -> list:
        a = self.inferVector(text)
        m = np.asarray(self.doc_vecs)
        sims = m @ a / np.maximum(
            np.linalg.norm(m, axis=1) * (np.linalg.norm(a) + 1e-12), 1e-12)
        order = np.argsort(-sims)[:n]
        return [self._labels[int(i)] for i in order]
