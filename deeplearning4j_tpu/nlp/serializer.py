"""Word-vector persistence.

Reference capability: org.deeplearning4j.models.embeddings.loader
.WordVectorSerializer (SURVEY.md §2.7): the word2vec text format
(header 'V D', then 'word v1 v2 ...' per line) readable by the original
word2vec tooling and gensim."""

from __future__ import annotations

import numpy as np


class WordVectorSerializer:
    @staticmethod
    def writeWord2VecModel(model, path):
        m = model.getWordVectorMatrix()
        with open(path, "w") as f:
            f.write(f"{m.shape[0]} {m.shape[1]}\n")
            for i in range(m.shape[0]):
                word = model.vocab.wordAtIndex(i)
                vec = " ".join(f"{x:.6f}" for x in m[i])
                f.write(f"{word} {vec}\n")

    @staticmethod
    def readWord2VecModel(path):
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec

        import jax.numpy as jnp

        with open(path) as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            model = Word2Vec(None, None, minWordFrequency=1, layerSize=d,
                             windowSize=5, negative=5, learningRate=0.025,
                             epochs=1, iterations=1, seed=0, batchSize=1024,
                             sampling=0, algorithm="skipgram")
            mat = np.zeros((v, d), np.float32)
            for i in range(v):
                parts = f.readline().rstrip("\n").split(" ")
                model.vocab.add(parts[0], 1)
                mat[i] = [float(x) for x in parts[1:d + 1]]
            model.syn0 = jnp.asarray(mat)
            model.syn1 = jnp.zeros_like(model.syn0)
        return model
