"""Word-vector persistence.

Reference capability: org.deeplearning4j.models.embeddings.loader
.WordVectorSerializer (SURVEY.md §2.7): the word2vec text format
(header 'V D', then 'word v1 v2 ...' per line) readable by the original
word2vec tooling and gensim."""

from __future__ import annotations

import numpy as np




def _model_from_vectors(words, mat):
    """Assemble a query-ready Word2Vec around loaded vectors (placeholder
    training hyperparameters; both the text and binary readers use this)."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    import jax.numpy as jnp

    model = Word2Vec(None, None, minWordFrequency=1, layerSize=mat.shape[1],
                     windowSize=5, negative=5, learningRate=0.025,
                     epochs=1, iterations=1, seed=0, batchSize=1024,
                     sampling=0, algorithm="skipgram")
    for w in words:
        model.vocab.add(w, 1)
    model.syn0 = jnp.asarray(mat)
    model.syn1 = jnp.zeros_like(model.syn0)
    return model


class WordVectorSerializer:
    @staticmethod
    def writeWord2VecModel(model, path):
        m = model.getWordVectorMatrix()
        with open(path, "w") as f:
            f.write(f"{m.shape[0]} {m.shape[1]}\n")
            for i in range(m.shape[0]):
                word = model.vocab.wordAtIndex(i)
                vec = " ".join(f"{x:.6f}" for x in m[i])
                f.write(f"{word} {vec}\n")

    @staticmethod
    def readWord2VecModel(path):
        with open(path, encoding="utf-8") as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            mat = np.zeros((v, d), np.float32)
            words = []
            for i in range(v):
                parts = f.readline().rstrip("\n").split(" ")
                words.append(parts[0])
                mat[i] = [float(x) for x in parts[1:d + 1]]
        return _model_from_vectors(words, mat)

    # -- Google word2vec BINARY format (reference: WordVectorSerializer
    # readBinaryModel/writeWordVectors(binary=true) — '<V> <D>\n' header
    # then per word: 'word ' + D little-endian float32 + '\n') -----------
    @staticmethod
    def writeWord2VecBinary(model, path):
        m = np.asarray(model.getWordVectorMatrix(), np.float32)
        with open(path, "wb") as f:
            f.write(f"{m.shape[0]} {m.shape[1]}\n".encode())
            for i in range(m.shape[0]):
                word = model.vocab.wordAtIndex(i)
                f.write(word.encode("utf-8") + b" ")
                f.write(m[i].astype("<f4").tobytes())
                f.write(b"\n")

    @staticmethod
    def readWord2VecBinary(path):
        with open(path, "rb") as f:
            header = f.readline().split()
            v, d = int(header[0]), int(header[1])
            mat = np.zeros((v, d), np.float32)
            words = []
            for i in range(v):
                word = bytearray()
                while True:
                    ch = f.read(1)
                    if not ch or ch == b" ":
                        break
                    word.extend(ch)
                mat[i] = np.frombuffer(f.read(4 * d), "<f4")
                nl = f.read(1)           # trailing newline
                if nl not in (b"\n", b""):
                    f.seek(-1, 1)        # some writers omit it
                words.append(word.decode("utf-8"))
        return _model_from_vectors(words, mat)

    @staticmethod
    def loadStaticModel(path):
        """Auto-detect text vs binary word2vec files (reference:
        WordVectorSerializer.loadStaticModel). Text is tried FIRST and
        fully parsed — a valid text model always succeeds, while binary
        payloads fail the utf-8 decode or the float parse and fall
        through; a byte-window probe would misroute text files whose
        window cuts a multibyte character."""
        try:
            return WordVectorSerializer.readWord2VecModel(path)
        except (UnicodeDecodeError, ValueError, IndexError):
            return WordVectorSerializer.readWord2VecBinary(path)
