"""INDArray: the ND4J tensor API re-expressed over jax.numpy.

Reference capability surface: org.nd4j.linalg.api.ndarray.INDArray /
BaseNDArray (SURVEY.md §2.3 "INDArray"). Semantics preserved: dtypes, views
with write-back, broadcasting, dup/assign, i-suffixed in-place ops, dimension
reductions. Execution model NOT preserved: ops build jax expressions that XLA
fuses, instead of one JNI->kernel dispatch per op (SURVEY.md §3.3).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _unwrap(x):
    if isinstance(x, INDArray):
        return x.jax()
    return x


def _coerce(x):
    """Like _unwrap but always yields a jax array (accepts python lists)."""
    return jnp.asarray(_unwrap(x))


class INDArray:
    """Stateful handle over an immutable jax.Array.

    Views: an INDArray produced by ``get``/``getRow``/``slice_`` holds only a
    reference to its parent plus the index expression — reads slice the
    parent's current buffer lazily (XLA fuses the slice), and in-place
    mutation writes back via functional ``.at[]`` updates, so aliasing is
    two-way like libnd4j's strided views.
    """

    __slots__ = ("_data", "_parent", "_index")
    __array_priority__ = 100  # beat numpy operator dispatch

    def __init__(self, data, parent: "INDArray | None" = None, index=None):
        self._parent = parent
        self._index = index
        if parent is not None:
            self._data = None  # views read through the parent
            return
        if isinstance(data, INDArray):
            data = data.jax()
        elif isinstance(data, (list, tuple, np.ndarray, int, float, bool)):
            data = jnp.asarray(data)
        self._data = data

    @property
    def _arr(self) -> jax.Array:
        if self._parent is not None:
            return self._parent._arr[self._index]
        return self._data

    # -- raw access ---------------------------------------------------------
    def jax(self) -> jax.Array:
        return self._arr

    def toNumpy(self) -> np.ndarray:
        return np.asarray(self._arr)

    numpy = toNumpy  # pythonic alias

    def __array__(self, dtype=None, copy=None):
        # without this, np.asarray(ind) falls back to the sequence
        # protocol and loops forever issuing one-element device gathers
        a = np.asarray(self._arr)
        return a.astype(dtype) if dtype is not None else a

    def _set(self, new_arr) -> "INDArray":
        """Rebind this handle; views write back through the parent chain."""
        cur = self._arr
        new_arr = jnp.asarray(new_arr, dtype=cur.dtype)
        if new_arr.shape != cur.shape:
            new_arr = jnp.broadcast_to(new_arr, cur.shape)
        if self._parent is not None:
            self._parent._set(self._parent._arr.at[self._index].set(new_arr))
        else:
            self._data = new_arr
        return self

    # -- shape / dtype ------------------------------------------------------
    def shape(self):
        return tuple(self._arr.shape)

    def rank(self) -> int:
        return self._arr.ndim

    def length(self) -> int:
        return int(self._arr.size)

    def size(self, dim: int) -> int:
        return int(self._arr.shape[dim])

    def isVector(self) -> bool:
        return self._arr.ndim == 1 or (
            self._arr.ndim == 2 and 1 in self._arr.shape
        )

    def isMatrix(self) -> bool:
        return self._arr.ndim == 2

    def isScalar(self) -> bool:
        return self._arr.ndim == 0 or self._arr.size == 1

    def rows(self) -> int:
        return int(self._arr.shape[0])

    def columns(self) -> int:
        return int(self._arr.shape[1])

    def dataType(self):
        return self._arr.dtype

    @property
    def dtype(self):
        return self._arr.dtype

    def castTo(self, dtype) -> "INDArray":
        return INDArray(self._arr.astype(dtype))

    # -- copy / assign ------------------------------------------------------
    def dup(self) -> "INDArray":
        return INDArray(self._arr)  # jax arrays are immutable: zero-copy dup

    def assign(self, other) -> "INDArray":
        return self._set(_unwrap(other))

    def ravel(self) -> "INDArray":
        return INDArray(self._arr.ravel())

    def flatten(self) -> "INDArray":
        return self.ravel()

    def reshape(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return INDArray(self._arr.reshape(shape))

    def transpose(self) -> "INDArray":
        return INDArray(self._arr.T)

    def permute(self, *axes) -> "INDArray":
        if len(axes) == 1 and isinstance(axes[0], (list, tuple)):
            axes = tuple(axes[0])
        return INDArray(jnp.transpose(self._arr, axes))

    def broadcast(self, *shape) -> "INDArray":
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return INDArray(jnp.broadcast_to(self._arr, shape))

    def repeat(self, dim: int, times: int) -> "INDArray":
        return INDArray(jnp.repeat(self._arr, times, axis=dim))

    def tile(self, *reps) -> "INDArray":
        return INDArray(jnp.tile(self._arr, reps))

    # -- elementwise arithmetic (functional + i-suffixed in-place) ----------
    def add(self, other) -> "INDArray":
        return INDArray(self._arr + _unwrap(other))

    def addi(self, other) -> "INDArray":
        return self._set(self._arr + _unwrap(other))

    def sub(self, other) -> "INDArray":
        return INDArray(self._arr - _unwrap(other))

    def subi(self, other) -> "INDArray":
        return self._set(self._arr - _unwrap(other))

    def rsub(self, other) -> "INDArray":
        return INDArray(_unwrap(other) - self._arr)

    def rsubi(self, other) -> "INDArray":
        return self._set(_unwrap(other) - self._arr)

    def mul(self, other) -> "INDArray":
        return INDArray(self._arr * _unwrap(other))

    def muli(self, other) -> "INDArray":
        return self._set(self._arr * _unwrap(other))

    def div(self, other) -> "INDArray":
        return INDArray(self._arr / _unwrap(other))

    def divi(self, other) -> "INDArray":
        return self._set(self._arr / _unwrap(other))

    def rdiv(self, other) -> "INDArray":
        return INDArray(_unwrap(other) / self._arr)

    def rdivi(self, other) -> "INDArray":
        return self._set(_unwrap(other) / self._arr)

    def neg(self) -> "INDArray":
        return INDArray(-self._arr)

    def negi(self) -> "INDArray":
        return self._set(-self._arr)

    def fmod(self, other) -> "INDArray":
        return INDArray(jnp.fmod(self._arr, _unwrap(other)))

    # python operators
    __add__ = add
    __radd__ = add
    __sub__ = sub
    __rsub__ = rsub
    __mul__ = mul
    __rmul__ = mul
    __truediv__ = div
    __rtruediv__ = rdiv
    __neg__ = neg

    def __pow__(self, p):
        return INDArray(self._arr ** _unwrap(p))

    def __matmul__(self, other):
        return self.mmul(other)

    def __eq__(self, other):  # elementwise, like ND4J eq()
        if isinstance(other, (INDArray, np.ndarray, jax.Array, int, float, bool)):
            return self.eq(other)
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, (INDArray, np.ndarray, jax.Array, int, float, bool)):
            return self.neq(other)
        return NotImplemented

    __hash__ = object.__hash__

    # -- broadcast-along-dimension ops (ND4J addRowVector etc.) -------------
    def addRowVector(self, row) -> "INDArray":
        return INDArray(self._arr + _coerce(row).reshape(1, -1))

    def addiRowVector(self, row) -> "INDArray":
        return self._set(self._arr + _coerce(row).reshape(1, -1))

    def addColumnVector(self, col) -> "INDArray":
        return INDArray(self._arr + _coerce(col).reshape(-1, 1))

    def addiColumnVector(self, col) -> "INDArray":
        return self._set(self._arr + _coerce(col).reshape(-1, 1))

    def mulRowVector(self, row) -> "INDArray":
        return INDArray(self._arr * _coerce(row).reshape(1, -1))

    def mulColumnVector(self, col) -> "INDArray":
        return INDArray(self._arr * _coerce(col).reshape(-1, 1))

    def subRowVector(self, row) -> "INDArray":
        return INDArray(self._arr - _coerce(row).reshape(1, -1))

    def divRowVector(self, row) -> "INDArray":
        return INDArray(self._arr / _coerce(row).reshape(1, -1))

    # -- linalg -------------------------------------------------------------
    def mmul(self, other) -> "INDArray":
        # GEMM -> stablehlo.dot_general -> MXU (replaces libnd4j MmulHelper /
        # cuBLAS routing, SURVEY.md §2.1)
        return INDArray(self._arr @ _unwrap(other))

    def mmuli(self, other) -> "INDArray":
        return self._set(self._arr @ _unwrap(other))

    def tensorMmul(self, other, axes) -> "INDArray":
        return INDArray(jnp.tensordot(self._arr, _unwrap(other), axes=axes))

    # -- reductions ---------------------------------------------------------
    def _reduce(self, fn, dims, keep=False):
        if not dims:
            return INDArray(fn(self._arr))
        axis = tuple(d if d >= 0 else d + self._arr.ndim for d in dims)
        return INDArray(fn(self._arr, axis=axis, keepdims=keep))

    def sum(self, *dims, keepDims=False) -> "INDArray":
        return self._reduce(jnp.sum, dims, keepDims)

    def mean(self, *dims, keepDims=False) -> "INDArray":
        return self._reduce(jnp.mean, dims, keepDims)

    def max(self, *dims, keepDims=False) -> "INDArray":
        return self._reduce(jnp.max, dims, keepDims)

    def min(self, *dims, keepDims=False) -> "INDArray":
        return self._reduce(jnp.min, dims, keepDims)

    def prod(self, *dims, keepDims=False) -> "INDArray":
        return self._reduce(jnp.prod, dims, keepDims)

    def std(self, *dims, keepDims=False) -> "INDArray":
        # ND4J std is the sample (Bessel-corrected) std
        if not dims:
            return INDArray(jnp.std(self._arr, ddof=1))
        axis = tuple(dims)
        return INDArray(jnp.std(self._arr, axis=axis, ddof=1, keepdims=keepDims))

    def var(self, *dims, keepDims=False) -> "INDArray":
        if not dims:
            return INDArray(jnp.var(self._arr, ddof=1))
        axis = tuple(dims)
        return INDArray(jnp.var(self._arr, axis=axis, ddof=1, keepdims=keepDims))

    def norm1(self, *dims) -> "INDArray":
        return self._reduce(lambda a, **k: jnp.sum(jnp.abs(a), **k), dims)

    def norm2(self, *dims) -> "INDArray":
        return self._reduce(
            lambda a, **k: jnp.sqrt(jnp.sum(a * a, **k)), dims
        )

    def normmax(self, *dims) -> "INDArray":
        return self._reduce(lambda a, **k: jnp.max(jnp.abs(a), **k), dims)

    def _arg_reduce(self, fn, dims):
        a = self._arr
        if not dims:
            return INDArray(fn(a))
        if len(dims) == 1:
            return INDArray(fn(a, axis=dims[0]))
        # multi-dim: move reduced axes last, flatten them, index within them
        dims = tuple(d % a.ndim for d in dims)
        keep = tuple(i for i in range(a.ndim) if i not in dims)
        moved = jnp.transpose(a, keep + dims)
        flat = moved.reshape(moved.shape[: len(keep)] + (-1,))
        return INDArray(fn(flat, axis=-1))

    def argMax(self, *dims) -> "INDArray":
        return self._arg_reduce(jnp.argmax, dims)

    def argMin(self, *dims) -> "INDArray":
        return self._arg_reduce(jnp.argmin, dims)

    def cumsum(self, dim: int = 0) -> "INDArray":
        return INDArray(jnp.cumsum(self._arr, axis=dim))

    def entropy(self) -> "INDArray":
        a = self._arr
        return INDArray(-jnp.sum(a * jnp.log(a)))

    # -- comparisons --------------------------------------------------------
    def gt(self, other) -> "INDArray":
        return INDArray(self._arr > _unwrap(other))

    def gte(self, other) -> "INDArray":
        return INDArray(self._arr >= _unwrap(other))

    def lt(self, other) -> "INDArray":
        return INDArray(self._arr < _unwrap(other))

    def lte(self, other) -> "INDArray":
        return INDArray(self._arr <= _unwrap(other))

    def eq(self, other) -> "INDArray":
        return INDArray(self._arr == _unwrap(other))

    def neq(self, other) -> "INDArray":
        return INDArray(self._arr != _unwrap(other))

    def equalsWithEps(self, other, eps: float = 1e-5) -> bool:
        o = _unwrap(other)
        if tuple(jnp.shape(o)) != self.shape():
            return False
        return bool(jnp.all(jnp.abs(self._arr - o) < eps))

    def equals(self, other) -> bool:
        return self.equalsWithEps(other, 1e-5)

    # -- indexing -----------------------------------------------------------
    def get(self, *index) -> "INDArray":
        """Strided view with write-back (NDArrayIndex capability)."""
        idx = index[0] if len(index) == 1 else tuple(index)
        return INDArray(self._arr[idx], parent=self, index=idx)

    def __getitem__(self, idx):
        return INDArray(self._arr[idx], parent=self, index=idx)

    def __setitem__(self, idx, value):
        self._set(self._arr.at[idx].set(_unwrap(value)))

    def put(self, idx, value) -> "INDArray":
        return self._set(self._arr.at[idx].set(_unwrap(value)))

    def putScalar(self, idx, value) -> "INDArray":
        # single int index is LINEAR (raveled) like ND4J putScalar(long, v),
        # matching getDouble's read side
        if isinstance(idx, (list, tuple)):
            idx = tuple(idx)
        elif self._arr.ndim > 1:
            idx = tuple(int(i) for i in np.unravel_index(int(idx), self._arr.shape))
        return self._set(self._arr.at[idx].set(value))

    def getRow(self, i: int) -> "INDArray":
        return INDArray(self._arr[i], parent=self, index=i)

    def getColumn(self, i: int) -> "INDArray":
        return INDArray(self._arr[:, i], parent=self, index=(slice(None), i))

    def getRows(self, *rows) -> "INDArray":
        return INDArray(self._arr[jnp.asarray(rows)])

    def getColumns(self, *cols) -> "INDArray":
        return INDArray(self._arr[:, jnp.asarray(cols)])

    def putRow(self, i: int, row) -> "INDArray":
        return self._set(self._arr.at[i].set(_unwrap(row)))

    def putColumn(self, i: int, col) -> "INDArray":
        return self._set(self._arr.at[:, i].set(_coerce(col).ravel()))

    def slice_(self, i: int, dim: int = 0) -> "INDArray":
        idx = tuple([slice(None)] * dim + [i])
        return INDArray(self._arr[idx], parent=self, index=idx)

    def getScalar(self, *idx) -> "INDArray":
        return INDArray(self._arr[tuple(idx)])

    def getDouble(self, *idx) -> float:
        if len(idx) == 1 and self._arr.ndim > 1:
            return float(self._arr.ravel()[idx[0]])
        return float(self._arr[tuple(idx)] if idx else self._arr)

    def getFloat(self, *idx) -> float:
        return self.getDouble(*idx)

    def getInt(self, *idx) -> int:
        return int(self.getDouble(*idx))

    # -- misc ---------------------------------------------------------------
    def isNaN(self) -> "INDArray":
        return INDArray(jnp.isnan(self._arr))

    def isInfinite(self) -> "INDArray":
        return INDArray(jnp.isinf(self._arr))

    def replaceWhere(self, replacement, mask) -> "INDArray":
        return self._set(
            jnp.where(_unwrap(mask).astype(bool), _unwrap(replacement), self._arr)
        )

    def __len__(self) -> int:
        return int(self._arr.shape[0])

    def __repr__(self) -> str:
        return f"INDArray{self.shape()}:{self._arr.dtype}\n{np.asarray(self._arr)}"

    def __str__(self) -> str:
        return str(np.asarray(self._arr))
