"""Transforms: elementwise math (reference: org.nd4j.linalg.ops.transforms.
Transforms + libnd4j legacy transform loops, SURVEY.md §2.1 "Legacy op loops").

Each call is a jnp expression XLA fuses into neighbors — the whole category of
hand-enumerated transform kernels collapses into the compiler.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import INDArray, _unwrap


def _t(fn):
    def wrapper(x, *args):
        return INDArray(fn(_unwrap(x), *[_unwrap(a) for a in args]))

    return wrapper


class Transforms:
    sigmoid = staticmethod(_t(jax.nn.sigmoid))
    tanh = staticmethod(_t(jnp.tanh))
    relu = staticmethod(_t(jax.nn.relu))
    relu6 = staticmethod(_t(jax.nn.relu6))
    leakyRelu = staticmethod(_t(lambda x, a=0.01: jax.nn.leaky_relu(x, a)))
    elu = staticmethod(_t(jax.nn.elu))
    selu = staticmethod(_t(jax.nn.selu))
    gelu = staticmethod(_t(jax.nn.gelu))
    softPlus = staticmethod(_t(jax.nn.softplus))
    softsign = staticmethod(_t(jax.nn.soft_sign))
    swish = staticmethod(_t(jax.nn.silu))
    mish = staticmethod(_t(lambda x: x * jnp.tanh(jax.nn.softplus(x))))
    hardSigmoid = staticmethod(_t(jax.nn.hard_sigmoid))
    hardTanh = staticmethod(_t(lambda x: jnp.clip(x, -1.0, 1.0)))
    exp = staticmethod(_t(jnp.exp))
    log = staticmethod(_t(jnp.log))
    log1p = staticmethod(_t(jnp.log1p))
    sqrt = staticmethod(_t(jnp.sqrt))
    abs = staticmethod(_t(jnp.abs))
    sign = staticmethod(_t(jnp.sign))
    floor = staticmethod(_t(jnp.floor))
    ceil = staticmethod(_t(jnp.ceil))
    round = staticmethod(_t(jnp.round))
    sin = staticmethod(_t(jnp.sin))
    cos = staticmethod(_t(jnp.cos))
    tan = staticmethod(_t(jnp.tan))
    asin = staticmethod(_t(jnp.arcsin))
    acos = staticmethod(_t(jnp.arccos))
    atan = staticmethod(_t(jnp.arctan))
    sinh = staticmethod(_t(jnp.sinh))
    cosh = staticmethod(_t(jnp.cosh))
    pow = staticmethod(_t(jnp.power))
    reciprocal = staticmethod(_t(lambda x: 1.0 / x))
    square = staticmethod(_t(jnp.square))
    cube = staticmethod(_t(lambda x: x * x * x))
    neg = staticmethod(_t(jnp.negative))
    max = staticmethod(_t(jnp.maximum))
    min = staticmethod(_t(jnp.minimum))
    clip = staticmethod(_t(jnp.clip))
    step = staticmethod(_t(lambda x: (x > 0).astype(x.dtype)))
    erf = staticmethod(_t(jax.scipy.special.erf))

    @staticmethod
    def softmax(x, dim: int = -1) -> INDArray:
        return INDArray(jax.nn.softmax(_unwrap(x), axis=dim))

    @staticmethod
    def logSoftmax(x, dim: int = -1) -> INDArray:
        return INDArray(jax.nn.log_softmax(_unwrap(x), axis=dim))

    @staticmethod
    def unitVec(x) -> INDArray:
        a = _unwrap(x)
        return INDArray(a / jnp.linalg.norm(a))

    @staticmethod
    def cosineSim(a, b) -> float:
        x, y = _unwrap(a).ravel(), _unwrap(b).ravel()
        return float(
            jnp.dot(x, y) / (jnp.linalg.norm(x) * jnp.linalg.norm(y))
        )

    @staticmethod
    def euclideanDistance(a, b) -> float:
        return float(jnp.linalg.norm(_unwrap(a).ravel() - _unwrap(b).ravel()))

    @staticmethod
    def manhattanDistance(a, b) -> float:
        return float(jnp.sum(jnp.abs(_unwrap(a).ravel() - _unwrap(b).ravel())))

    @staticmethod
    def allEuclideanDistances(a, b) -> INDArray:
        x, y = _unwrap(a), _unwrap(b)
        d2 = (
            jnp.sum(x * x, 1, keepdims=True)
            - 2.0 * x @ y.T
            + jnp.sum(y * y, 1)[None, :]
        )
        return INDArray(jnp.sqrt(jnp.maximum(d2, 0.0)))

    @staticmethod
    def allCosineSimilarities(a, b) -> INDArray:
        x, y = _unwrap(a), _unwrap(b)
        xn = x / jnp.linalg.norm(x, axis=1, keepdims=True)
        yn = y / jnp.linalg.norm(y, axis=1, keepdims=True)
        return INDArray(xn @ yn.T)
