"""ND4J-capability tensor layer (reference: nd4j/nd4j-api-parent/nd4j-api,
org.nd4j.linalg.api.ndarray.INDArray + org.nd4j.linalg.factory.Nd4j —
SURVEY.md §2.3).

TPU-first design: an :class:`INDArray` is a thin stateful handle over an
immutable ``jax.Array`` resident on device. "In-place" ND4J ops (``addi`` …)
rebind the handle (views write back through ``.at[]`` functional updates);
everything lowers to XLA, so chained ops fuse instead of dispatching one
kernel per call the way libnd4j did.
"""

from deeplearning4j_tpu.ndarray.ndarray import INDArray
from deeplearning4j_tpu.ndarray.factory import Nd4j
from deeplearning4j_tpu.ndarray.transforms import Transforms

__all__ = ["INDArray", "Nd4j", "Transforms"]
