"""Nd4j: static factory + exec surface (reference:
org.nd4j.linalg.factory.Nd4j, SURVEY.md §2.3).

Stateful RNG streams mirror org.nd4j.linalg.api.rng (SURVEY.md §2.3 "Random")
but are built on jax's counter-based threefry: the stream holds a key and
splits per draw, so draws are reproducible under setSeed yet safe to use from
jitted code via explicit key passing.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ndarray.ndarray import INDArray, _unwrap


class _RandomStream:
    """Stateful RNG facade over jax.random (threefry counter RNG).

    Key creation is LAZY: building a PRNG key initializes the XLA
    backend, and `import deeplearning4j_tpu` must stay side-effect free
    so multi-host programs can call jax.distributed.initialize (via
    MultiHost.initialize) after importing the framework."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None

    def setSeed(self, seed: int):
        self._seed = seed
        self._key = None

    def nextKey(self) -> jax.Array:
        if self._key is None:
            self._key = jax.random.key(self._seed)
        self._key, sub = jax.random.split(self._key)
        return sub

    def nextDouble(self) -> float:
        return float(jax.random.uniform(self.nextKey(), ()))

    def nextGaussian(self) -> float:
        return float(jax.random.normal(self.nextKey(), ()))

    def nextInt(self, bound: int) -> int:
        return int(jax.random.randint(self.nextKey(), (), 0, bound))


class Nd4j:
    """Array factory; the capability analogue of org.nd4j.linalg.factory.Nd4j."""

    _rng = _RandomStream(123)
    default_dtype = jnp.float32

    # -- rng ----------------------------------------------------------------
    @classmethod
    def getRandom(cls) -> _RandomStream:
        return cls._rng

    @classmethod
    def setSeed(cls, seed: int):
        cls._rng.setSeed(seed)

    # -- creation -----------------------------------------------------------
    @classmethod
    def create(cls, *args, dtype=None) -> INDArray:
        """create(data), create(data, shape), or create(*shape).

        A tuple of ints (or int args) is a shape -> zeros, like ND4J
        create(int[]); lists / ndarrays / INDArrays are data.
        """
        dtype = dtype or cls.default_dtype
        first = args[0]
        is_shape_tuple = isinstance(first, tuple) and all(
            isinstance(x, (int, np.integer)) for x in first
        )
        if (
            isinstance(first, (list, tuple, np.ndarray, INDArray, jax.Array))
            and not is_shape_tuple
        ):
            data = jnp.asarray(_unwrap(first), dtype=dtype)
            if len(args) == 2 and isinstance(args[1], (list, tuple)):
                return INDArray(data.reshape(tuple(args[1])))
            return INDArray(data)
        shape = tuple(first) if is_shape_tuple and len(args) == 1 else args
        return INDArray(jnp.zeros(tuple(int(s) for s in shape), dtype=dtype))

    @classmethod
    def zeros(cls, *shape, dtype=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return INDArray(jnp.zeros(shape, dtype=dtype or cls.default_dtype))

    @classmethod
    def ones(cls, *shape, dtype=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return INDArray(jnp.ones(shape, dtype=dtype or cls.default_dtype))

    @classmethod
    def zerosLike(cls, arr) -> INDArray:
        return INDArray(jnp.zeros_like(_unwrap(arr)))

    @classmethod
    def onesLike(cls, arr) -> INDArray:
        return INDArray(jnp.ones_like(_unwrap(arr)))

    @classmethod
    def valueArrayOf(cls, shape, value, dtype=None) -> INDArray:
        if isinstance(shape, int):
            shape = (shape,)
        return INDArray(
            jnp.full(tuple(shape), value, dtype=dtype or cls.default_dtype)
        )

    @classmethod
    def scalar(cls, value, dtype=None) -> INDArray:
        return INDArray(jnp.asarray(value, dtype=dtype or cls.default_dtype))

    @classmethod
    def eye(cls, n: int, dtype=None) -> INDArray:
        return INDArray(jnp.eye(n, dtype=dtype or cls.default_dtype))

    @classmethod
    def arange(cls, *args, dtype=None) -> INDArray:
        return INDArray(jnp.arange(*args, dtype=dtype or cls.default_dtype))

    @classmethod
    def linspace(cls, start, stop, num, dtype=None) -> INDArray:
        return INDArray(
            jnp.linspace(start, stop, int(num), dtype=dtype or cls.default_dtype)
        )

    @classmethod
    def rand(cls, *shape, seed=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        key = jax.random.key(seed) if seed is not None else cls._rng.nextKey()
        return INDArray(jax.random.uniform(key, shape, dtype=cls.default_dtype))

    @classmethod
    def randn(cls, *shape, seed=None) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        key = jax.random.key(seed) if seed is not None else cls._rng.nextKey()
        return INDArray(jax.random.normal(key, shape, dtype=cls.default_dtype))

    @classmethod
    def randomBernoulli(cls, p: float, *shape) -> INDArray:
        if len(shape) == 1 and isinstance(shape[0], (list, tuple)):
            shape = tuple(shape[0])
        return INDArray(
            jax.random.bernoulli(cls._rng.nextKey(), p, shape).astype(
                cls.default_dtype
            )
        )

    # -- combination --------------------------------------------------------
    @classmethod
    def concat(cls, dim: int, *arrs) -> INDArray:
        return INDArray(jnp.concatenate([_unwrap(a) for a in arrs], axis=dim))

    @classmethod
    def vstack(cls, *arrs) -> INDArray:
        return INDArray(jnp.vstack([_unwrap(a) for a in arrs]))

    @classmethod
    def hstack(cls, *arrs) -> INDArray:
        return INDArray(jnp.hstack([_unwrap(a) for a in arrs]))

    @classmethod
    def stack(cls, dim: int, *arrs) -> INDArray:
        return INDArray(jnp.stack([_unwrap(a) for a in arrs], axis=dim))

    @classmethod
    def pile(cls, *arrs) -> INDArray:
        return cls.stack(0, *arrs)

    @classmethod
    def expandDims(cls, arr, dim: int) -> INDArray:
        return INDArray(jnp.expand_dims(_unwrap(arr), dim))

    @classmethod
    def squeeze(cls, arr, dim: int) -> INDArray:
        return INDArray(jnp.squeeze(_unwrap(arr), axis=dim))

    @classmethod
    def where(cls, cond, x, y) -> INDArray:
        return INDArray(jnp.where(_unwrap(cond).astype(bool), _unwrap(x), _unwrap(y)))

    @classmethod
    def gemm(cls, a, b, transposeA=False, transposeB=False, alpha=1.0) -> INDArray:
        A, B = _unwrap(a), _unwrap(b)
        if transposeA:
            A = A.T
        if transposeB:
            B = B.T
        return INDArray(alpha * (A @ B))

    @classmethod
    def matmul(cls, a, b) -> INDArray:
        return INDArray(_unwrap(a) @ _unwrap(b))

    @classmethod
    def diag(cls, arr) -> INDArray:
        return INDArray(jnp.diag(_unwrap(arr)))

    @classmethod
    def sort(cls, arr, dim: int = -1, ascending: bool = True) -> INDArray:
        s = jnp.sort(_unwrap(arr), axis=dim)
        if not ascending:
            s = jnp.flip(s, axis=dim)
        return INDArray(s)

    @classmethod
    def fromNumpy(cls, arr: np.ndarray) -> INDArray:
        return INDArray(jnp.asarray(arr))

    # -- npy serde (reference: Nd4j.writeNpy / nd4j-serde, SURVEY.md §2.3) --
    @classmethod
    def writeNpy(cls, arr, path: str):
        np.save(path, np.asarray(_unwrap(arr)), allow_pickle=False)

    @classmethod
    def readNpy(cls, path: str) -> INDArray:
        return INDArray(jnp.asarray(np.load(path, allow_pickle=False)))
