"""Asynchronous advantage actor-critic (A3C) with real actor threads.

Reference capability: rl4j's async learning family —
org.deeplearning4j.rl4j.learning.async.a3c.A3CDiscreteDense with
AsyncLearning + AsyncThreadDiscrete workers (SURVEY.md §2.7; VERDICT.md
round-1 row 44 "reference has async A3C workers ... here sync A2C
only"). Architecture kept, device usage adapted: N host actor threads
step their own environment copies against parameter snapshots and push
n-step rollouts into a queue (env stepping is host work and threads
overlap it), while the single learner drains the queue and applies ONE
jitted donated update per rollout — the hogwild "apply gradients from
any thread" scheme is deliberately replaced by a serialized learner
because concurrent in-place updates to a jax pytree would just contend
on the device lock, and the queue gives the same actor/learner
decoupling. The synchronous batched variant lives in a2c.py; this class
exists for workload parity (thread scaling, stale-policy actors) and
API parity."""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.rl.a2c import A2CConfiguration, A2CDiscreteDense


@dataclass
class A3CConfiguration(A2CConfiguration):
    queueSize: int = 64


class A3CDiscreteDense(A2CDiscreteDense):
    """Async actor threads + serialized learner over the A2C core."""

    def __init__(self, mdp_factory, conf: A3CConfiguration):
        super().__init__(mdp_factory, conf)
        self._mdp_factory = mdp_factory

    def train(self):
        conf = self.conf
        rollouts: queue.Queue = queue.Queue(maxsize=conf.queueSize)
        finished: list[float] = []
        finished_lock = threading.Lock()
        stop = threading.Event()
        steps_done = [0]
        steps_lock = threading.Lock()

        # actors read this snapshot; the learner swaps it after updates.
        # jnp.copy = fresh DEVICE buffers (safe against the learner's
        # donation, and actors don't re-upload params every env step the
        # way a numpy snapshot would)
        snap_copy = jax.jit(
            lambda p: jax.tree_util.tree_map(jnp.copy, p))
        snapshot = {"params": snap_copy(self.params)}
        infer = jax.jit(self._net)

        def actor(tid):
            env = self._mdp_factory()
            rng = np.random.default_rng(conf.seed * 1000 + tid)
            obs = env.reset()
            ep_reward = 0.0
            while not stop.is_set():
                params = snapshot["params"]
                t_obs, t_act, t_rew, t_done = [], [], [], []
                for _ in range(conf.nSteps):
                    logits, _ = infer(
                        params, jnp.asarray(obs, jnp.float32)[None])
                    p = np.asarray(jax.nn.softmax(logits[0]))
                    a = int(rng.choice(self.n_act, p=p / p.sum()))
                    nxt, r, d, _ = env.step(a)
                    ep_reward += r
                    t_obs.append(np.asarray(obs, np.float32))
                    t_act.append(a)
                    t_rew.append(r)
                    t_done.append(float(d))
                    obs = nxt
                    if d:
                        with finished_lock:
                            finished.append(ep_reward)
                        ep_reward = 0.0
                        obs = env.reset()
                # bootstrap with the value of the trailing observation
                _, v_last = infer(params,
                                  jnp.asarray(obs, jnp.float32)[None])
                ret = float(np.asarray(v_last)[0])
                rets = []
                for r, d in zip(reversed(t_rew), reversed(t_done)):
                    ret = r + conf.gamma * ret * (1.0 - d)
                    rets.append(ret)
                rets.reverse()
                batch = (np.stack(t_obs), np.asarray(t_act, np.int32),
                         np.asarray(rets, np.float32))
                with steps_lock:
                    steps_done[0] += len(t_obs)
                    done_all = steps_done[0] >= conf.maxStep
                try:
                    rollouts.put(batch, timeout=1.0)
                except queue.Full:
                    pass
                if done_all:
                    stop.set()

        threads = [threading.Thread(target=actor, args=(i,), daemon=True,
                                    name=f"dl4j:train:a3c-actor-{i}")
                   for i in range(conf.nThreads)]
        for t in threads:
            t.start()

        # learner: drain rollouts, apply jitted updates, publish snapshots
        while not stop.is_set() or not rollouts.empty():
            try:
                obs_b, act_b, ret_b = rollouts.get(timeout=0.2)
            except queue.Empty:
                continue
            _loss, self.params, self.opt = self._step_fn(
                self.params, self.opt, obs_b, act_b, ret_b, self._t)
            self._t += 1
            snapshot["params"] = snap_copy(self.params)
        for t in threads:
            t.join(timeout=5.0)
        return finished
