"""Advantage actor-critic (synchronous A2C).

Reference capability: rl4j's A3C (org.deeplearning4j.rl4j.learning.async
.a3c.A3CDiscreteDense, SURVEY.md §2.7). The reference runs asynchronous
actor threads against a shared DL4J net; on TPU the idiomatic equivalent
is SYNCHRONOUS batched advantage actor-critic: N environment copies
stepped on host, one jitted update over the joint rollout (the async
hogwild scheme exists only to keep GPUs busy from the JVM — a compiled
batched step makes it unnecessary)."""

from __future__ import annotations

import copy
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.rl.dqn import _init_mlp, _mlp


@dataclass
class A2CConfiguration:
    seed: int = 0
    nThreads: int = 8            # parity name: number of parallel envs
    nSteps: int = 5              # rollout length
    maxStep: int = 20000
    gamma: float = 0.95
    learningRate: float = 7e-4
    entropyCoef: float = 0.01
    valueCoef: float = 0.5
    hidden: tuple = (64,)


class A2CDiscreteDense:
    def __init__(self, mdp_factory, conf: A2CConfiguration):
        """mdp_factory: zero-arg callable producing fresh MDP instances."""
        self.conf = conf
        self.envs = [mdp_factory() for _ in range(conf.nThreads)]
        probe = self.envs[0]
        obs_dim = int(np.prod(probe.observationShape()))
        self.n_act = probe.actionSpaceSize()
        key = jax.random.key(conf.seed)
        trunk_sizes = (obs_dim,) + tuple(conf.hidden)
        self.params = {
            "trunk": _init_mlp(key, trunk_sizes + (conf.hidden[-1],)),
            "pi": _init_mlp(jax.random.fold_in(key, 1),
                            (conf.hidden[-1], self.n_act)),
            "v": _init_mlp(jax.random.fold_in(key, 2),
                           (conf.hidden[-1], 1)),
        }
        self.opt = {
            "m": jax.tree_util.tree_map(jnp.zeros_like, self.params),
            "v": jax.tree_util.tree_map(jnp.zeros_like, self.params),
        }
        self._t = 0
        self._rng = np.random.default_rng(conf.seed)
        self._step_fn = self._build()
        self._logits_fn = jax.jit(self._net)

    def _net(self, params, x):
        h = jax.nn.relu(_mlp(params["trunk"], x))
        return _mlp(params["pi"], h), _mlp(params["v"], h)[..., 0]

    def _build(self):
        conf = self.conf

        def step(params, opt, obs, act, ret, t):
            def loss_fn(p):
                logits, value = self._net(p, obs)
                logp = jax.nn.log_softmax(logits)
                probs = jnp.exp(logp)
                adv = ret - value
                pg = -jnp.mean(
                    jnp.take_along_axis(logp, act[:, None], 1)[:, 0]
                    * jax.lax.stop_gradient(adv))
                v_loss = jnp.mean(adv ** 2)
                entropy = -jnp.mean(jnp.sum(probs * logp, axis=1))
                return (pg + conf.valueCoef * v_loss
                        - conf.entropyCoef * entropy)

            loss, g = jax.value_and_grad(loss_fn)(params)
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree_util.tree_map(
                lambda m_, g_: b1 * m_ + (1 - b1) * g_, opt["m"], g)
            v = jax.tree_util.tree_map(
                lambda v_, g_: b2 * v_ + (1 - b2) * g_ * g_, opt["v"], g)
            tt = t + 1
            params = jax.tree_util.tree_map(
                lambda p_, m_, v_: p_ - conf.learningRate
                * (m_ / (1 - b1 ** tt))
                / (jnp.sqrt(v_ / (1 - b2 ** tt)) + eps),
                params, m, v)
            return loss, params, {"m": m, "v": v}

        return jax.jit(step, donate_argnums=(0, 1))

    def train(self):
        conf = self.conf
        obs = np.stack([env.reset() for env in self.envs])
        steps = 0
        ep_rewards = [0.0] * len(self.envs)
        finished: list[float] = []
        while steps < conf.maxStep:
            traj_obs, traj_act, traj_rew, traj_done = [], [], [], []
            for _ in range(conf.nSteps):
                logits, _ = self._logits_fn(self.params,
                                            jnp.asarray(obs, jnp.float32))
                p = np.asarray(jax.nn.softmax(logits))
                acts = np.array([self._rng.choice(self.n_act, p=pi)
                                 for pi in p])
                nxt, rews, dones = [], [], []
                for i, env in enumerate(self.envs):
                    o, r, d, _ = env.step(int(acts[i]))
                    ep_rewards[i] += r
                    if d:
                        finished.append(ep_rewards[i])
                        ep_rewards[i] = 0.0
                        o = env.reset()
                    nxt.append(o)
                    rews.append(r)
                    dones.append(float(d))
                traj_obs.append(obs)
                traj_act.append(acts)
                traj_rew.append(np.asarray(rews, np.float32))
                traj_done.append(np.asarray(dones, np.float32))
                obs = np.stack(nxt)
                steps += len(self.envs)
            # bootstrap returns
            _, v_last = self._logits_fn(self.params,
                                        jnp.asarray(obs, jnp.float32))
            ret = np.asarray(v_last)
            returns = []
            for r, d in zip(reversed(traj_rew), reversed(traj_done)):
                ret = r + conf.gamma * ret * (1.0 - d)
                returns.append(ret)
            returns.reverse()
            flat_obs = np.concatenate(traj_obs).astype(np.float32)
            flat_act = np.concatenate(traj_act).astype(np.int32)
            flat_ret = np.concatenate(returns).astype(np.float32)
            loss, self.params, self.opt = self._step_fn(
                self.params, self.opt, flat_obs, flat_act, flat_ret,
                self._t)
            self._t += 1
        return finished

    def play(self, mdp, max_steps=200) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            logits, _ = self._logits_fn(
                self.params, jnp.asarray(obs, jnp.float32)[None])
            obs, r, done, _ = mdp.step(int(jnp.argmax(logits[0])))
            total += r
            if done:
                break
        return total
