from deeplearning4j_tpu.rl.mdp import MDP, SimpleGridWorld  # noqa: F401
from deeplearning4j_tpu.rl.dqn import (  # noqa: F401
    DQNPolicy, QLearningConfiguration, QLearningDiscreteDense)
from deeplearning4j_tpu.rl.a2c import (  # noqa: F401
    A2CConfiguration, A2CDiscreteDense)
from deeplearning4j_tpu.rl.a3c import (  # noqa: F401
    A3CConfiguration, A3CDiscreteDense)
