"""DQN (Q-learning with replay + target network).

Reference capability: rl4j org.deeplearning4j.rl4j.learning.sync.qlearning
.discrete.QLearningDiscreteDense (SURVEY.md §2.7): epsilon-greedy
environment interaction (host), experience replay, and a double-buffered
target network. The learning update is ONE jitted step over a sampled
batch (gather-max target + Huber loss + Adam), params donated — the
reference instead fits its DL4J net per batch through the per-op path."""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp


@dataclass
class QLearningConfiguration:
    seed: int = 0
    maxEpochStep: int = 200
    maxStep: int = 15000
    expRepMaxSize: int = 10000
    batchSize: int = 64
    targetDqnUpdateFreq: int = 100
    updateStart: int = 100
    rewardFactor: float = 1.0
    gamma: float = 0.95
    errorClamp: float = 1.0
    minEpsilon: float = 0.05
    epsilonDecay: float = 0.995
    learningRate: float = 1e-3
    hidden: tuple = (64, 64)
    # reference: QLearning.QLConfiguration.doubleDQN — decouple action
    # selection (online net) from evaluation (target net)
    doubleDQN: bool = False
    # reference: rl4j dueling DQN factory — Q = V + A - mean(A)
    dueling: bool = False

    @staticmethod
    def builder():
        return _QConfBuilder()


class _QConfBuilder:
    def __init__(self):
        self._kw = {}

    def __getattr__(self, item):
        def setter(v):
            self._kw[item] = v
            return self

        return setter

    def build(self):
        return QLearningConfiguration(**self._kw)


def _init_mlp(key, sizes, dueling=False):
    params = []
    trunk = sizes[:-1] if dueling else sizes
    for i, (a, b) in enumerate(zip(trunk[:-1], trunk[1:])):
        k = jax.random.fold_in(key, i)
        params.append({
            "W": jax.random.normal(k, (a, b)) * np.sqrt(2.0 / a),
            "b": jnp.zeros((b,)),
        })
    if dueling:
        h, n_act = sizes[-2], sizes[-1]
        kv = jax.random.fold_in(key, 101)
        ka = jax.random.fold_in(key, 102)
        params.append({
            "Wv": jax.random.normal(kv, (h, 1)) * np.sqrt(2.0 / h),
            "bv": jnp.zeros((1,)),
            "Wa": jax.random.normal(ka, (h, n_act)) * np.sqrt(2.0 / h),
            "ba": jnp.zeros((n_act,)),
        })
    return params


def _mlp(params, x):
    head = params[-1]
    if "Wv" in head:        # dueling: shared trunk -> V and A streams
        for p in params[:-1]:
            x = jax.nn.relu(x @ p["W"] + p["b"])
        v = x @ head["Wv"] + head["bv"]                    # [N, 1]
        a = x @ head["Wa"] + head["ba"]                    # [N, n_act]
        return v + a - jnp.mean(a, axis=1, keepdims=True)
    for i, p in enumerate(params):
        x = x @ p["W"] + p["b"]
        if i < len(params) - 1:
            x = jax.nn.relu(x)
    return x


class DQNPolicy:
    """Greedy policy over a trained Q-network (reference: DQNPolicy)."""

    def __init__(self, params, n_actions):
        self.params = params
        self.n_actions = n_actions
        self._fn = jax.jit(_mlp)

    def nextAction(self, obs) -> int:
        q = self._fn(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(q[0]))

    def play(self, mdp, max_steps=1000) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done, _ = mdp.step(self.nextAction(obs))
            total += r
            if done:
                break
        return total


class QLearningDiscreteDense:
    def __init__(self, mdp, conf: QLearningConfiguration):
        self.mdp = mdp
        self.conf = conf
        obs_dim = int(np.prod(mdp.observationShape()))
        n_act = mdp.actionSpaceSize()
        sizes = (obs_dim,) + tuple(conf.hidden) + (n_act,)
        key = jax.random.key(conf.seed)
        self.params = _init_mlp(key, sizes, dueling=conf.dueling)
        # real copy: params is donated each step, so the target must not
        # alias its buffers (f(donate(a), a) is invalid)
        self.target = jax.tree_util.tree_map(
            lambda x: jnp.array(x, copy=True), self.params)
        self.opt = {
            "m": jax.tree_util.tree_map(jnp.zeros_like, self.params),
            "v": jax.tree_util.tree_map(jnp.zeros_like, self.params),
        }
        self.n_act = n_act
        self._train_step = self._build()
        self._rng = np.random.default_rng(conf.seed)
        self.epsilon = 1.0
        self._t = 0

    def _build(self):
        gamma = self.conf.gamma
        lr = self.conf.learningRate
        clamp = self.conf.errorClamp

        def step(params, target, opt, obs, act, rew, nxt, done, t):
            def loss_fn(p):
                q = _mlp(p, obs)
                q_sa = jnp.take_along_axis(q, act[:, None], axis=1)[:, 0]
                if self.conf.doubleDQN:
                    # double DQN: online net picks, target net evaluates
                    a_star = jnp.argmax(_mlp(p, nxt), axis=1)
                    q_next = jnp.take_along_axis(
                        _mlp(target, nxt), a_star[:, None], axis=1)[:, 0]
                    q_next = jax.lax.stop_gradient(q_next)
                else:
                    q_next = jnp.max(_mlp(target, nxt), axis=1)
                y = rew + gamma * q_next * (1.0 - done)
                err = q_sa - jax.lax.stop_gradient(y)
                # Huber with errorClamp delta
                abs_e = jnp.abs(err)
                return jnp.mean(jnp.where(
                    abs_e <= clamp, 0.5 * err * err,
                    clamp * (abs_e - 0.5 * clamp)))

            loss, g = jax.value_and_grad(loss_fn)(params)
            b1, b2, eps = 0.9, 0.999, 1e-8
            m = jax.tree_util.tree_map(
                lambda m_, g_: b1 * m_ + (1 - b1) * g_, opt["m"], g)
            v = jax.tree_util.tree_map(
                lambda v_, g_: b2 * v_ + (1 - b2) * g_ * g_, opt["v"], g)
            tt = t + 1
            params = jax.tree_util.tree_map(
                lambda p, m_, v_: p - lr * (m_ / (1 - b1 ** tt))
                / (jnp.sqrt(v_ / (1 - b2 ** tt)) + eps),
                params, m, v)
            return loss, params, {"m": m, "v": v}

        return jax.jit(step, donate_argnums=(0, 2))

    def train(self):
        conf = self.conf
        obs_dim = int(np.prod(self.mdp.observationShape()))
        cap = conf.expRepMaxSize
        buf = {
            "obs": np.zeros((cap, obs_dim), np.float32),
            "act": np.zeros(cap, np.int32),
            "rew": np.zeros(cap, np.float32),
            "nxt": np.zeros((cap, obs_dim), np.float32),
            "done": np.zeros(cap, np.float32),
        }
        size = pos = 0
        steps = 0
        rewards = []
        q_fn = jax.jit(_mlp)
        while steps < conf.maxStep:
            obs = self.mdp.reset()
            ep_rew = 0.0
            for _ in range(conf.maxEpochStep):
                if self._rng.random() < self.epsilon:
                    a = int(self._rng.integers(self.n_act))
                else:
                    q = q_fn(self.params,
                             jnp.asarray(obs, jnp.float32)[None])
                    a = int(jnp.argmax(q[0]))
                nxt, r, done, _ = self.mdp.step(a)
                r *= conf.rewardFactor
                buf["obs"][pos] = obs
                buf["act"][pos] = a
                buf["rew"][pos] = r
                buf["nxt"][pos] = nxt
                buf["done"][pos] = float(done)
                pos = (pos + 1) % cap
                size = min(size + 1, cap)
                obs = nxt
                ep_rew += r
                steps += 1
                if size >= conf.updateStart:
                    idx = self._rng.integers(0, size, conf.batchSize)
                    loss, self.params, self.opt = self._train_step(
                        self.params, self.target, self.opt,
                        buf["obs"][idx], buf["act"][idx], buf["rew"][idx],
                        buf["nxt"][idx], buf["done"][idx], self._t)
                    self._t += 1
                    if self._t % conf.targetDqnUpdateFreq == 0:
                        self.target = jax.tree_util.tree_map(
                            lambda x: jnp.array(x, copy=True), self.params)
                if done or steps >= conf.maxStep:
                    break
            self.epsilon = max(conf.minEpsilon,
                               self.epsilon * conf.epsilonDecay)
            rewards.append(ep_rew)
        return rewards

    def getPolicy(self) -> DQNPolicy:
        return DQNPolicy(self.params, self.n_act)
