"""MDP interface + a built-in test environment.

Reference capability: rl4j's MDP abstraction (org.deeplearning4j.rl4j.mdp
.MDP wrapping gym envs, SURVEY.md §2.7). The gym dependency is replaced by
a plain protocol: reset() -> obs, step(a) -> (obs, reward, done, info)."""

from __future__ import annotations

import numpy as np


class MDP:
    def reset(self):
        raise NotImplementedError

    def step(self, action):
        raise NotImplementedError

    def actionSpaceSize(self) -> int:
        raise NotImplementedError

    def observationShape(self) -> tuple:
        raise NotImplementedError

    def isDone(self) -> bool:
        raise NotImplementedError


class SimpleGridWorld(MDP):
    """n x n grid, start top-left, goal bottom-right; actions URDL; -0.01
    per step, +1 at goal; episode cap 4*n steps. Solvable by short-horizon
    Q-learning — the in-repo equivalent of rl4j's toy MDPs."""

    ACTIONS = [(-1, 0), (0, 1), (1, 0), (0, -1)]

    def __init__(self, n=4, seed=0):
        self.n = n
        self._pos = (0, 0)
        self._steps = 0
        self._done = False

    def observationShape(self):
        return (2,)

    def actionSpaceSize(self):
        return 4

    def _obs(self):
        return np.asarray(self._pos, np.float32) / (self.n - 1)

    def reset(self):
        self._pos = (0, 0)
        self._steps = 0
        self._done = False
        return self._obs()

    def isDone(self):
        return self._done

    def step(self, action):
        dr, dc = self.ACTIONS[int(action)]
        r = min(max(self._pos[0] + dr, 0), self.n - 1)
        c = min(max(self._pos[1] + dc, 0), self.n - 1)
        self._pos = (r, c)
        self._steps += 1
        at_goal = self._pos == (self.n - 1, self.n - 1)
        self._done = at_goal or self._steps >= 4 * self.n
        reward = 1.0 if at_goal else -0.01
        return self._obs(), reward, self._done, {}
