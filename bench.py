"""Benchmarks for the five BASELINE.md configs on one TPU chip.

Default (driver contract): runs the flagship BERT-base MLM config and
prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

`python bench.py --all` additionally measures LeNet-MNIST images/sec,
ResNet-50 images/sec + MFU (the BASELINE.json north star), GravesLSTM
char-RNN tokens/sec, Word2Vec SkipGram words/sec and the serving-latency
smoke, MERGING all results into BENCH_ALL.json (one JSON object per
config) — VERDICT.md round-1 item 3: every BASELINE.md row gets a
measured number. `--only name[,name]` re-records a subset (off-TPU runs
land under platform-suffixed keys and never displace chip rows);
`--words N` sizes the Word2Vec corpus.

Baseline note (BASELINE.md): the reference publishes no in-tree numbers
(`published: {}`), so vs_baseline is reported against BASELINE.json's
north-star target of 40% MFU where MFU is defined (BERT, ResNet-50):
vs_baseline = measured_MFU / 0.40; >1.0 beats the target. Configs whose
baseline rows have no target metric report vs_baseline = null.
Peak bf16 throughput per TPU v5e chip: 197 TFLOP/s.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

V5E_PEAK_BF16 = 197e12
MFU_TARGET = 0.40


def bert_train_flops_per_step(cfg, batch, seq, n_masked):
    """fwd+bwd ~= 3x fwd. Per token, each layer's matmuls cost
    2*h*3h (QKV) + 2*h*h (attn out) + 2*2*h*f (FFN pair); attention
    adds 2*2*T*h per token (QK^T and PV). The tied LM head scores ONLY
    the n_masked masked positions per example (standard BERT pretraining
    head; the model gathers before the vocab matmul, so counting full-
    sequence head FLOPs would inflate MFU)."""
    h, f, L, v = cfg.hidden, cfg.ffn, cfg.num_layers, cfg.vocab_size
    tokens = batch * seq
    fwd = tokens * L * (2 * h * 3 * h + 2 * h * h + 4 * h * f)
    fwd += tokens * L * (4 * seq * h)
    fwd += batch * n_masked * 2 * h * v
    return 3 * fwd


# keep the old name importable
train_flops_per_step = bert_train_flops_per_step


def bench_bert():
    import jax

    from deeplearning4j_tpu.models.bert import (
        BertConfig, BertTrainer, synthetic_mlm_batch)
    from deeplearning4j_tpu.parallel.mesh import MeshConfig

    cfg = BertConfig(vocab_size=30522, hidden=768, num_layers=12,
                     num_heads=12, ffn=3072, max_len=512)
    batch, seq = 16, 512
    mesh = MeshConfig(data=1, devices=jax.devices()[:1]).build()
    trainer = BertTrainer(cfg, mesh, lr=1e-4)

    # K optimizer steps per launch (lax.scan): measures the chip, not the
    # experimental axon tunnel's ~25 ms per-dispatch RPC latency. The
    # tunnel's throughput also varies ~2x between runs, so take the best
    # of several trials (standard peak-throughput reporting).
    k = 20  # k=10 -> 62.7 ms/step, k=20 -> 54.6 ms/step (launch amortized)
    # batch sweep (same session, 12-step launches): b16 42.8% MFU,
    # b24 41.0%, b32 40.9% -> b16 is the v5e sweet spot for this config
    stacks = [synthetic_mlm_batch(cfg, batch, seq, seed=s)
              for s in range(k)]
    tokens_k = np.stack([s[0] for s in stacks])
    labels_k = np.stack([s[1] for s in stacks])

    def best(repeats):
        float(trainer.train_steps(tokens_k, labels_k,
                                  repeats=repeats)[-1])  # compile
        b = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(trainer.train_steps(tokens_k, labels_k,
                                      repeats=repeats)[-1])
            b = min(b, time.perf_counter() - t0)
        return b

    # slope between 1-pass and 3-pass launches over the same K batches:
    # cancels the tunnel's fixed per-launch RTT (r3's /k division left
    # ~5 ms/step of RTT in the number)
    t1 = best(1)
    t2 = best(3)
    dt = (t2 - t1) / (2 * k)

    tokens_per_sec = batch * seq / dt
    mfu = bert_train_flops_per_step(
        cfg, batch, seq, trainer._max_preds(seq)) / dt / V5E_PEAK_BF16
    return {
        "metric": "bert_base_mlm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / MFU_TARGET, 3),
        "mfu": round(mfu, 4),
    }


def _fit_throughput(net, batches, epochs_warm=2, epochs_meas=4):
    """Steady-state fit() throughput in examples/sec (includes the host
    loop, i.e. what a user's training run actually sees)."""
    net.fit(batches, epochs_warm)   # compile + warm
    n_examples = sum(np.asarray(b[0]).shape[0] for b in batches)
    t0 = time.perf_counter()
    net.fit(batches, epochs_meas)
    # fit syncs per-listener only; force one final device read
    float(net.score((np.asarray(batches[0][0]), np.asarray(batches[0][1]))))
    dt = time.perf_counter() - t0
    return n_examples * epochs_meas / dt


def _scan_throughput(net, X_k, y_k, trials=3, repeats_long=5):
    """Steady-state step throughput in examples/sec via fitMultiBatch,
    SLOPE-timed: per-step time is the slope between a 1-pass and an
    R-pass launch over the same K stacked batches, which cancels the
    axon tunnel's fixed ~25-100 ms per-launch round trip. (r3 divided
    one launch's wall time by K, leaving RTT/K inside every number —
    up to 2x understatement for the fast configs; ROUND4_NOTES.)"""
    import jax

    k = X_k.shape[0]
    n_examples = k * X_k.shape[1]
    X_k = jax.device_put(jax.numpy.asarray(X_k))
    y_k = jax.device_put(jax.numpy.asarray(y_k))

    def best(repeats):
        float(net.fitMultiBatch(X_k, y_k, repeats=repeats)[-1])  # compile
        dt = float("inf")
        for _ in range(trials):
            t0 = time.perf_counter()
            float(net.fitMultiBatch(X_k, y_k, repeats=repeats)[-1])
            dt = min(dt, time.perf_counter() - t0)
        return dt

    t1 = best(1)
    # grow the long span until the extra device work clears the ~0.1 s
    # tunnel-RTT jitter, else the slope of a sub-ms-step config drowns
    # in noise (LeNet's first slope came out NEGATIVE)
    repeats = repeats_long
    while True:
        t2 = best(repeats)
        if t2 - t1 > 0.6 or repeats >= 625:
            break
        repeats *= 5
    per_pass = (t2 - t1) / (repeats - 1)
    return n_examples / per_pass


def bench_lenet():
    from deeplearning4j_tpu.models.zoo import LeNet

    net = LeNet().init()
    rng = np.random.default_rng(0)
    bsz, nb = 512, 8
    X_k = rng.normal(size=(nb, bsz, 1, 28, 28)).astype(np.float32)
    y_k = np.stack([np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, bsz)] for _ in range(nb)])
    ips = _scan_throughput(net, X_k, y_k)
    return {
        "metric": "lenet_mnist_images_per_sec",
        "value": round(ips, 1),
        "unit": "images/sec",
        "vs_baseline": None,  # BASELINE row 1: functional parity only
    }


def resnet50_train_flops(batch):
    """ResNet-50 fwd = 4.1 GMACs per 224x224 image = 8.2e9 FLOP in the
    2*MAC convention that XLA's cost model and the 197 TFLOP/s v5e peak
    both use; train ~= 3x fwd. (PR-10 cost-model audit: the old 4.1e9
    counted multiply-accumulates as single FLOPs against a peak quoted
    in real FLOP/s — a 2x MFU understatement. cost_analysis() of this
    repo's ResNet50 train step measures 2.25e10 at batch 1, within 10%
    of 3*8.2e9; chip rows recorded before PR 10 carry the old
    convention until re-measured.)"""
    return 3 * 8.2e9 * batch


def bench_resnet50():
    from deeplearning4j_tpu.models.zoo import ResNet50

    import jax.numpy as jnp

    # bfloat16: the TPU-idiomatic training dtype (reference analog:
    # dataType(DataType.HALF)); batch 256 saturates the chip. BN is
    # one-pass f32-accumulated. r4 analysis (tools/RESNET_MFU.md,
    # slope-timed): mid/late bottleneck blocks run at 52-96% of peak
    # under XLA — the ~16-17% model MFU concentrates in the early
    # stages (f=64/128 leaves the 128x128 MXU half-fed; BN stat passes
    # double the s0 forward) and the composed backward. A hand-written
    # Pallas fused bottleneck kernel measured SLOWER than XLA at every
    # stage shape (tools/probe_fused_block.py), and remat / layout /
    # s2d-stem / bf16-stat levers all measured dead, so this row is
    # shape-limited, not scheduling-limited.
    net = ResNet50(numClasses=1000, dataType="bfloat16").init()
    rng = np.random.default_rng(0)
    bsz, k = 256, 16
    X_k = rng.normal(size=(k, bsz, 3, 224, 224)).astype(np.float32)
    y_k = np.stack([np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, bsz)] for _ in range(k)])
    X_k = jnp.asarray(X_k, jnp.bfloat16)
    y_k = jnp.asarray(y_k, jnp.bfloat16)
    ips = _scan_throughput(net, X_k, y_k, trials=3)
    mfu = resnet50_train_flops(1) * ips / V5E_PEAK_BF16
    return {
        "metric": "resnet50_imagenet_images_per_sec_per_chip",
        "value": round(ips, 1),
        "unit": "images/sec",
        "dataType": "bfloat16",
        "vs_baseline": round(mfu / MFU_TARGET, 3),
        "mfu": round(mfu, 4),
    }


def bench_resnet_etl():
    """With-input-pipeline companion to the peak resnet50 number (VERDICT
    round-2 weak item 5): how fast the parallel image ETL
    (datasets/parallel_etl.py) can produce 224x224 training batches from
    disk on THIS host. Reported next to the synthetic-tensor peak; on a
    multi-core host the worker pool scales decode linearly, this
    environment has a single usable core."""
    import os
    import tempfile
    import time as _t

    from PIL import Image

    from deeplearning4j_tpu.datasets import (
        FileSplit, ParallelImageDataSetIterator)

    root = tempfile.mkdtemp(prefix="bench_etl_")
    rng = np.random.default_rng(0)
    n = 512
    for cls in ("a", "b"):
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(n // 2):
            arr = rng.integers(0, 255, (224, 224, 3), np.uint8)
            Image.fromarray(arr, "RGB").save(
                os.path.join(d, f"{i}.jpg"), quality=85)
    workers = max(1, os.cpu_count() or 1)
    it = ParallelImageDataSetIterator(
        FileSplit(root), 224, 224, 3, batchSize=64, numWorkers=workers)
    # time the FULL epoch including worker startup: with a parallel pool
    # most decode overlaps the first next(), so excluding it would
    # measure queue drain, not sustained ETL rate
    t0 = _t.perf_counter()
    count = 0
    while it.hasNext():
        count += np.asarray(it.next().getFeatures()).shape[0]
    dt = _t.perf_counter() - t0
    import shutil

    shutil.rmtree(root, ignore_errors=True)
    return {
        "metric": "resnet50_image_etl_img_per_sec",
        "value": round(count / dt, 1),
        "unit": "images/sec",
        "vs_baseline": None,
        "host_workers": workers,
        "note": ("host-side decode+augment rate feeding the chip; "
                 "scales with host cores (this host has "
                 f"{os.cpu_count()})"),
    }


def bench_etl(n_images=256, side=224):
    """Streaming-ETL engine scaling curve (ISSUE 6 acceptance): img/s of
    the persistent-pool + shm-ring pipeline at 1/2/4/8 workers on a
    synthetic 224x224 JPEG tree, against the legacy single-worker
    equivalent path (per-image full bilinear resize to float32 + a
    pickled-float32 IPC roundtrip per batch — the cost model of the
    pre-ISSUE-6 iterator that recorded 210.9 img/s), plus the trainer
    etl-wait fraction at MNIST scale with and without the
    DevicePrefetcher."""
    import os
    import pickle
    import shutil
    import tempfile
    import time as _t

    from PIL import Image

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.datasets import (
        FileSplit, ParallelImageDataSetIterator, set_default_depth)
    from deeplearning4j_tpu.datasets.image import (
        NativeImageLoader, _bilinear_resize_chw)

    root = tempfile.mkdtemp(prefix="bench_etl_")
    rng = np.random.default_rng(0)
    for cls in ("a", "b"):
        d = os.path.join(root, cls)
        os.makedirs(d)
        for i in range(n_images // 2):
            arr = rng.integers(0, 255, (side, side, 3), np.uint8)
            Image.fromarray(arr, "RGB").save(
                os.path.join(d, f"{i}.jpg"), quality=85)
    files = sorted(os.path.join(root, c, f)
                   for c in ("a", "b")
                   for f in os.listdir(os.path.join(root, c)))
    batch = 64

    # -- legacy equivalent: the pre-rebuild per-image pipeline ---------------
    loader = NativeImageLoader(side, side, 3)
    t0 = _t.perf_counter()
    for lo in range(0, n_images, batch):
        feats = []
        for p in files[lo:lo + batch]:
            hwc = loader._decode_hwc(p)
            feats.append(_bilinear_resize_chw(hwc, side, side))
        arr = np.stack(feats).astype(np.float32)
        arr = pickle.loads(pickle.dumps(arr))  # the mp.Queue byte cost
    legacy = n_images / (_t.perf_counter() - t0)

    # -- the new engine: serial baseline + 1/2/4/8-worker pool curve ---------
    def epoch_rate(**kw):
        it = ParallelImageDataSetIterator(
            FileSplit(root), side, side, 3, batchSize=batch, **kw)
        # warm epoch: pool fork + page cache; the persistent pool makes
        # epoch 2+ the steady state an epoch-boundary refork would hide
        for _ in it:
            pass
        best = 0.0
        for _ in range(2):   # best-of-2: the shared CI host is noisy
            it.reset()
            t0 = _t.perf_counter()
            count = 0
            for ds in it:
                count += np.asarray(ds.getFeatures()).shape[0]
            best = max(best, count / (_t.perf_counter() - t0))
        it.close()
        return round(best, 1)

    serial = epoch_rate(transport="serial")
    # uint8 output = the streaming configuration (decode stays uint8 end
    # to end, normalize happens on device via DevicePrefetcher)
    curve = {w: epoch_rate(numWorkers=w, transport="shm",
                           floatOutput=False)
             for w in (1, 2, 4, 8)}
    float_out_8 = epoch_rate(numWorkers=8, transport="shm")
    shutil.rmtree(root, ignore_errors=True)

    # -- trainer etl-wait fraction at MNIST scale ----------------------------
    # blocking = the trainer eats split+pad+mask+transfer at every
    # next(); prefetch = the DevicePrefetcher does that in its producer
    # thread and the trainer pops a staged device batch. (On a CPU
    # backend the jitted step itself saturates the host cores, so a
    # decode-heavy input pipeline cannot truly overlap — the img/s curve
    # above carries that contention; this measurement isolates the
    # prefetcher's steady-state wait at MNIST scale, where input prep is
    # cheaper than the step, i.e. the regime a fed chip runs in.)
    from deeplearning4j_tpu.datasets import MnistDataSetIterator
    from deeplearning4j_tpu.nn import (
        DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
        OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam

    def wait_fraction(depth):
        telemetry.get_registry().reset()
        set_default_depth(depth)
        try:
            conf = (NeuralNetConfiguration.Builder().seed(0)
                    .updater(Adam(1e-3)).list()
                    .layer(DenseLayer.Builder(nOut=256,
                                              activation="relu").build())
                    .layer(DenseLayer.Builder(nOut=256,
                                              activation="relu").build())
                    .layer(OutputLayer.Builder().nOut(10)
                           .activation("softmax").build())
                    .setInputType(InputType.feedForward(784))
                    .build())
            net = MultiLayerNetwork(conf)
            net.init()
            it = MnistDataSetIterator(128, num_examples=2048)
            net.fit(it, 3)
            reg = telemetry.get_registry()
            etl = reg.histogram("dl4j_etl_wait_seconds",
                                labelnames=("loop",)).labels(loop="fit")
            step = reg.histogram("dl4j_step_seconds",
                                 labelnames=("loop",)).labels(loop="fit")
            return etl.sum / max(step.sum, 1e-9)
        finally:
            set_default_depth(2)
            telemetry.get_registry().reset()

    blocking_frac = wait_fraction(0)
    prefetch_frac = wait_fraction(2)

    w8 = curve[8]
    return {
        "metric": "etl_img_per_sec_8_workers",
        "value": w8,
        "unit": "images/sec",
        "vs_baseline": None,
        "img_per_sec_by_workers": curve,
        "img_per_sec_serial": serial,
        "img_per_sec_8_workers_float_out": float_out_8,
        "legacy_single_worker_img_per_sec": round(legacy, 1),
        "speedup_vs_legacy_at_8_workers": round(w8 / legacy, 2),
        "etl_wait_fraction_blocking": round(blocking_frac, 4),
        "etl_wait_fraction_prefetch": round(prefetch_frac, 4),
        "host_cores": os.cpu_count(),
        "note": (f"{n_images} synthetic {side}x{side} JPEGs, batch "
                 f"{batch}; steady-state epoch (persistent pool, warm "
                 "page cache); curve is the uint8-to-device "
                 "configuration over the shm ring; legacy = pre-ISSUE-6 "
                 "path (full bilinear resize to f32 + pickled-f32 IPC) "
                 "at 1 worker; wait fractions are "
                 "sum(dl4j_etl_wait)/sum(dl4j_step) for a 784-256-256-10 "
                 "MLP on MNIST, batch 128, DevicePrefetcher off/on; "
                 "worker counts above host_cores oversubscribe, and on "
                 "the CPU backend the step itself occupies the cores "
                 "the decode workers need"),
    }


def bench_graves_lstm():
    """Char-RNN throughput + fraction-of-peak (VERDICT round-2 item 6;
    r3 item 5 closed by the r4 slope-timing correction).

    r4 revision: the r3 number (1.65-2.3M tokens/s, 5.7% MFU) carried
    the axon tunnel's ~100 ms per-launch RTT divided by only K=8 steps —
    slope timing (two launch lengths, fixed cost cancels) measures the
    same config at ~8M tokens/s, 21-22% MFU. The >=4*T sequential
    recurrence chain bounds the remaining gap to peak (each optimizer
    step serializes 4*T dependent scan iterations whose per-step matmul
    is latency- not throughput-sized); K-steps-per-launch was already
    saturated — the 'amortization headroom' r3 asked about was tunnel
    overhead, not chip time."""
    from deeplearning4j_tpu.models.zoo import TextGenerationLSTM

    vocab, seq, bsz = 77, 100, 1024
    net = TextGenerationLSTM(vocabSize=vocab, hidden=256,
                             seqLength=seq).init()
    rng = np.random.default_rng(0)
    k = 8
    ids = rng.integers(0, vocab, (k, bsz, seq + 1))
    X_k = np.stack([np.eye(vocab, dtype=np.float32)[ids[i, :, :-1]]
                    .transpose(0, 2, 1) for i in range(k)])
    y_k = np.stack([np.eye(vocab, dtype=np.float32)[ids[i, :, 1:]]
                    .transpose(0, 2, 1) for i in range(k)])
    eps = _scan_throughput(net, X_k, y_k)
    toks = eps * seq
    h = 256
    fwd_flops = 8 * h * (vocab + h) + 8 * h * (h + h) + 2 * h * vocab
    mfu = toks * 3 * fwd_flops / V5E_PEAK_BF16
    return {
        "metric": "graves_lstm_char_rnn_tokens_per_sec",
        "value": round(toks, 1),
        "unit": "tokens/sec",
        "vs_baseline": None,  # BASELINE row 3: reference unpublished
        "batch": bsz,
        "mfu": round(mfu, 5),
        "bound": ("sequential recurrence (>=4*T dependent scan steps "
                  "per optimizer step; slope-timed, launch RTT "
                  "excluded)"),
    }


def bench_word2vec(total_words=10_000_000):
    """Steady-state SGNS words/s on a >=10M-word zipf corpus (VERDICT
    round-2 item 5: the old 150k-word number measured warm-up, and
    mainstream CPU implementations reach hundreds of k words/s — the
    TPU path must be measured at scale). One warm epoch builds the token
    cache + compiles the scan; the timed epoch covers the full per-epoch
    pipeline: vectorized subsampling -> native pair-gen -> one-launch
    scan with on-device negative draws."""
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    rng = np.random.default_rng(0)
    vocab, sent_len = 100_000, 25
    n_sent = total_words // sent_len
    zipf = 1.0 / np.arange(1, vocab + 1) ** 1.05
    p = zipf / zipf.sum()
    flat = rng.choice(vocab, n_sent * sent_len, p=p)
    names = np.char.add("w", flat.astype("U7"))
    sents = [" ".join(row) for row in
             names.reshape(n_sent, sent_len)]
    w2v = (Word2Vec.Builder().minWordFrequency(1).layerSize(128)
           .windowSize(5).negativeSample(5).batchSize(8192)
           .epochs(1).seed(1).iterate(sents).build())
    w2v.buildVocab()
    # two warm epochs: token cache + compile, AND stabilize the k-bucket
    # (a subsampling-jitter bucket bump would recompile inside the timed
    # epoch and corrupt the measurement)
    w2v.fit()
    w2v.fit()
    _ = np.asarray(w2v.syn0).sum()
    t0 = time.perf_counter()
    w2v.fit()   # steady-state epoch
    _ = np.asarray(w2v.syn0).sum()  # sync
    dt = time.perf_counter() - t0
    wps = total_words / dt
    # Primitive roofline (r4, slope-timed: tools/probe_scatter.py):
    # sorted row scatter sustains ~125M rows/s; each pair moves
    # ~2*(2+k_neg) rows (gather + scatter across both tables), ~3.8
    # pairs/word after subsampling at window 5. r5 correction: at the
    # production batch width the scatter phase already RUNS at that
    # roofline (0.32 ms for 57k rows/step) — the binding bound is the
    # step's gather/einsum math floor plus scan overhead, not the
    # scatter (tools/probe_w2v_step.py E vs A variants).
    k_neg, pairs_per_word = 5, 3.8
    rows_per_word = pairs_per_word * 2 * (2 + k_neg)
    roof_wps = 125e6 / rows_per_word
    import jax

    if jax.default_backend() != "tpu":
        # the bound analysis below describes the chip; an off-TPU row
        # (bench.py --only word2vec on this host) must not carry it
        return {
            "metric": "word2vec_skipgram_words_per_sec",
            "value": round(wps, 1),
            "unit": "words/sec",
            "vs_baseline": None,
            "corpus_words": total_words,
            "bound": (f"{jax.default_backend()} fallback run (XLA host "
                      "scan); the TPU roofline analysis applies only on "
                      "the chip"),
        }
    return {
        "metric": "word2vec_skipgram_words_per_sec",
        "value": round(wps, 1),
        "unit": "words/sec",
        "vs_baseline": None,  # BASELINE row 5: reference unpublished
        "corpus_words": total_words,
        "scatter_roofline_words_per_sec": round(roof_wps, 1),
        "frac_of_roofline": round(wps / roof_wps, 4),
        "bound": ("r5 epoch = ~2.0s fully-device ETL (subsample + "
                  "slice-shift windows + compaction; was 4.4s device + "
                  "~3.5s host in r4) + ~7.4s training scan at 1.57 "
                  "ms/step (pooled negatives; per-step floor: 0.49 ms "
                  "gather/einsum math + 0.32 ms sort+scatter, scatter "
                  "AT its 125M rows/s roofline). Probes: "
                  "tools/probe_w2v_step.py (batch sweep peaks at 8192; "
                  "segment-sum dedup, unsorted scatter, bulk-draw "
                  "hoist, scan unroll all measured slower), "
                  "tools/probe_w2v_pairgen.py (scalar gathers 0.19 "
                  "GB/s -> slice-shifts; searchsorted and row-scatter "
                  "compaction 4-10x slower). Host numpy reference on "
                  "this 1-core host: ~24k words/s."),
    }


def bench_serving_latency(n_requests=300):
    """ISSUE 2 serving smoke: p50/p99 sync predict latency through the
    DynamicBatcher on a warmed AOT bucket ladder, at batch 1 and batch
    32. Single-client, so batch-1 latency INCLUDES the max-latency flush
    window (1 ms here) the batcher holds open for co-travelers — that
    window is the price of coalescing and belongs in the number."""
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import BucketLadder, InferenceSession

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(128).nOut(256)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(10).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    net = MultiLayerNetwork(conf).init()
    session = InferenceSession(max_latency=0.001)
    session.register("bench", net, example_shape=(128,),
                     ladder=BucketLadder((1, 8, 32)), warmup=True)
    rng = np.random.default_rng(0)
    x1 = rng.normal(size=(128,)).astype(np.float32)
    x32 = rng.normal(size=(32, 128)).astype(np.float32)

    def percentiles(x, n):
        for _ in range(10):         # settle the queue/thread path
            session.predict("bench", x)
        lat = np.empty(n)
        for i in range(n):
            t0 = time.perf_counter()
            session.predict("bench", x)
            lat[i] = time.perf_counter() - t0
        return np.percentile(lat * 1e3, [50, 99])

    p50_1, p99_1 = percentiles(x1, n_requests)
    p50_32, p99_32 = percentiles(x32, max(50, n_requests // 4))
    session.close()
    return {
        "metric": "serving_latency_p50_ms_batch1",
        "value": round(float(p50_1), 3),
        "unit": "ms",
        "vs_baseline": None,
        "p99_batch1_ms": round(float(p99_1), 3),
        "p50_batch32_ms": round(float(p50_32), 3),
        "p99_batch32_ms": round(float(p99_32), 3),
        "requests": n_requests,
        "note": ("single-client sync predict through DynamicBatcher on a "
                 "warmed (1,8,32) AOT ladder; batch-1 includes the 1 ms "
                 "coalescing flush window"),
    }


def _host_bound() -> bool:
    """True off-chip: the row's value reflects host capacity (cores,
    scheduler, dispatch overhead), not the model math — benchdiff
    skips regression-gating host-bound rows on non-chip platforms
    (ISSUE 13 satellite; the ROADMAP 'meaningless off-chip' debt)."""
    import jax

    return jax.default_backend() != "tpu"


def bench_serving_load(duration=2.0, deadline_ms=30.0,
                       rows_per_request=16):
    """ISSUE 8: open-loop load generator for the multi-replica serving
    path. Poisson arrivals at fixed offered QPS (requests of
    `rows_per_request` examples), swept geometrically from light load
    to saturation, for three configs on the same MLP: the single-
    batcher path, a 4-replica work-stealing ReplicaSet (one replica
    per CPU mesh device), and int8-PTQ replicas. Every request carries
    a `deadline_ms` timeout, so "saturation throughput" is the max
    completed-rows/s AT THAT DEADLINE — late answers don't count.

    A fourth phase drives the replica config at ~2x its saturation
    with admission control on and a 15/85 high/batch priority mix:
    production overload should shed the best-effort tail (429 +
    Retry-After) while high-priority p99 holds near its unloaded
    value.

    Open loop matters: a closed-loop client backs off exactly when the
    server struggles, hiding the queueing collapse this bench exists
    to measure (the coordinated-omission trap)."""
    import threading
    from collections import Counter as _Counter

    import jax

    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.precision import quantize
    from deeplearning4j_tpu.serving import (
        AdmissionController, BucketLadder, InferenceSession,
        QueueFullError, ServingTimeout, ShedError)

    n_dev = len(jax.devices())
    deadline_s = deadline_ms / 1e3
    ladder = BucketLadder((rows_per_request, 2 * rows_per_request,
                           4 * rows_per_request))
    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows_per_request, 128)).astype(np.float32)

    def build_net(seed=7, layers=16, width=192):
        # deep-narrow on purpose: per-op matmuls too small for XLA CPU
        # to split across cores, so one dispatch occupies ~one core —
        # the honest CPU stand-in for one-replica-per-chip (a TPU
        # executable can't borrow a neighbor chip's ALUs either). Wide
        # nets let the SINGLE path grab every core per dispatch and
        # measure nothing but this container's 2-core ceiling.
        b = (NeuralNetConfiguration.Builder().seed(seed).list()
             .layer(DenseLayer.Builder().nIn(128).nOut(width)
                    .activation("relu").build()))
        for _ in range(layers - 1):
            b = b.layer(DenseLayer.Builder().nOut(width)
                        .activation("relu").build())
        conf = (b.layer(OutputLayer.Builder().nOut(10)
                        .activation("softmax")
                        .lossFunction(LossFunction.MCXENT).build())
                .build())
        return MultiLayerNetwork(conf).init()

    net = build_net()

    def open_loop(session, qps, mix=None, run_s=None):
        """One offered-load point. mix: {priority: fraction} (None =
        all normal). Returns completion stats."""
        run_s = duration if run_s is None else run_s
        lats = {"high": [], "normal": [], "batch": []}
        outcomes = _Counter()
        pending = []
        lock = threading.Lock()
        arr = np.random.default_rng(1234)
        prios, cum = (["normal"], [1.0]) if mix is None else (
            list(mix), list(np.cumsum([mix[p] for p in mix])))
        start = time.perf_counter()
        t_next = start
        while t_next < start + run_s:
            now = time.perf_counter()
            if t_next > now:
                time.sleep(t_next - now)
            u = arr.random()
            prio = prios[int(np.searchsorted(cum, u))] \
                if len(prios) > 1 else prios[0]
            t0 = time.perf_counter()
            try:
                f = session.predict_async("m", X, timeout=deadline_s,
                                          priority=prio)

                def cb(fut, t0=t0, prio=prio):
                    err = fut.exception()
                    with lock:
                        if err is None:
                            lats[prio].append(time.perf_counter() - t0)
                            outcomes["ok"] += 1
                        elif isinstance(err, (ServingTimeout,
                                              TimeoutError)):
                            outcomes["timeout"] += 1
                        else:
                            outcomes["error"] += 1

                f.add_done_callback(cb)
                pending.append(f)
            except ShedError:
                outcomes[f"shed_{prio}"] += 1
            except QueueFullError:
                outcomes["rejected"] += 1
            outcomes["offered"] += 1
            t_next += arr.exponential(1.0 / qps)
        # drain stragglers: every future resolves by its deadline (the
        # batcher fails late ones with timeout_queued when it reaches
        # them), so one deadline past the window covers the tail
        t_stop = time.perf_counter() + deadline_s + 0.3
        while time.perf_counter() < t_stop and \
                any(not f.done() for f in pending[-64:]):
            time.sleep(0.01)
        wall = time.perf_counter() - start
        all_lats = [v for p in lats.values() for v in p]

        def pct(vals, q):
            return (round(float(np.percentile(np.asarray(vals) * 1e3,
                                              q)), 2)
                    if vals else None)

        return {
            "offered_qps": round(qps, 1),
            "completed_rows_per_s": round(
                outcomes["ok"] * rows_per_request / wall, 1),
            "p50_ms": pct(all_lats, 50), "p99_ms": pct(all_lats, 99),
            "p99_high_ms": pct(lats["high"], 99),
            "p99_batch_ms": pct(lats["batch"], 99),
            "outcomes": dict(outcomes),
            "shed_rate": round(
                sum(v for k, v in outcomes.items()
                    if k.startswith("shed_") or k == "rejected")
                / max(outcomes["offered"], 1), 4),
        }

    def sweep(session):
        points, best, flat = [], 0.0, 0
        qps = 25.0
        while qps <= 3200 and flat < 2:
            p = open_loop(session, qps)
            points.append(p)
            thr = p["completed_rows_per_s"]
            if thr > best * 1.08:
                best, flat = max(best, thr), 0
            else:
                flat += 1
            qps *= 1.8
        return points, round(best, 1)

    results, sat = {}, {}
    configs = [
        ("single", dict(), net),
        (f"replicas{n_dev}", dict(replicas=n_dev), net),
        (f"replicas{n_dev}_int8", dict(replicas=n_dev),
         quantize(net, [(X, None)], example_shape=(128,))),
    ]
    for label, reg_kw, model in configs:
        session = InferenceSession(max_latency=0.001, queue_size=256)
        session.register("m", model, example_shape=(128,),
                         ladder=ladder, warmup=True, **reg_kw)
        open_loop(session, 50, run_s=0.5)          # settle threads
        # this container's throughput swings ±40% run to run (see the
        # word2vec/etl bench notes): sweep twice, merge per-point by
        # best completed rate, report best-of-both saturation
        merged = {}
        best = 0.0
        for _ in range(2):
            points, peak = sweep(session)
            best = max(best, peak)
            for p in points:
                q = p["offered_qps"]
                if q not in merged or p["completed_rows_per_s"] > \
                        merged[q]["completed_rows_per_s"]:
                    merged[q] = p
        results[label] = [merged[q] for q in sorted(merged)]
        sat[label] = round(best, 1)
        session.close()

    # -- overload: 2x saturation, high vs best-effort under admission --
    repl = f"replicas{n_dev}"
    # budget sized for the SLO: 8 standing requests against ~10k+
    # rows/s of replica capacity keeps worst-case queueing around
    # 10-20 ms — the high class must never wait behind a deep
    # best-effort backlog (batch capped at 50% of even that)
    session = InferenceSession(
        max_latency=0.001, queue_size=256,
        admission=AdmissionController(default_budget=8))
    session.register("m", net, example_shape=(128,), ladder=ladder,
                     warmup=True, replicas=n_dev)
    open_loop(session, 50, run_s=0.5)
    sat_qps = sat[repl] / rows_per_request
    unloaded = open_loop(session, max(10.0, 0.15 * sat_qps),
                         mix={"high": 1.0})
    overload = open_loop(session, 2.0 * sat_qps,
                         mix={"high": 0.15, "batch": 0.85},
                         run_s=2 * duration)
    session.close()
    hi_ratio = (overload["p99_high_ms"] / unloaded["p99_high_ms"]
                if overload["p99_high_ms"] and unloaded["p99_high_ms"]
                else None)
    shed_batch = sum(v for k, v in overload["outcomes"].items()
                     if k == "shed_batch")
    ratio = round(sat[repl] / max(sat["single"], 1e-9), 2)
    return {
        "metric": "serving_load_saturation_ratio",
        "value": ratio,
        "unit": f"x single-batcher rows/s at {deadline_ms:.0f}ms deadline",
        "vs_baseline": None,
        "host_bound": _host_bound(),
        "saturation_rows_per_s": sat,
        "sweep": results,
        "overload": {
            "unloaded_high": unloaded, "at_2x": overload,
            "high_p99_ratio": (round(hi_ratio, 2)
                               if hi_ratio is not None else None),
            "batch_sheds": int(shed_batch),
        },
        "devices": n_dev,
        "host_cores": __import__("os").cpu_count(),
        "rows_per_request": rows_per_request,
        "note": (f"open-loop Poisson, {rows_per_request}-row requests, "
                 f"{deadline_ms:.0f}ms request deadline; saturation = "
                 "max completed rows/s meeting the deadline (best of 2 "
                 "sweeps; this host swings +-40% run to run). CAVEAT: "
                 "this container has 2 cores under the 4-device mesh "
                 "(2:1 oversubscribed) and a lone XLA CPU dispatch "
                 "already uses both cores, so measured concurrent-exec "
                 "headroom is only 1.2-1.9x (probed) and overload p99 "
                 "tails are OS-scheduler noise — the >=2.5x acceptance "
                 "ratio and the 1.5x high-p99 bound need >=1 core (or "
                 "chip) per replica; re-record on chip "
                 "(`python bench.py --only serving_load`)"),
    }


def bench_decode(prompt_len=256, max_new=32, n_requests=6):
    """ISSUE 12: open-loop decode bench over the v2 engine arms —
    plain (PR-8 per-token prefill), chunked prefill, prefix-cache hit,
    and speculative decoding — recording tokens/s and TTFT p50/p99
    per arm plus the boundary counts that explain them. One tiny
    transformer pair (draft = half-width) so the row measures the
    ENGINE (boundary bookkeeping, dispatch count, adoption), not the
    model. benchdiff direction: the headline value is tokens/s
    (higher is better); the per-arm ttft_*_ms details are
    informational."""
    from deeplearning4j_tpu.serving import (
        DecodeEngine, SpeculativeConfig, TransformerDecodeModel)

    def mk(hidden=64, n_layers=2, seed=5):
        return TransformerDecodeModel.init(
            vocab=256, hidden=hidden, n_layers=n_layers, n_heads=2,
            max_len=prompt_len + max_new + 64, max_slots=4, page=32,
            max_pages_per_slot=(prompt_len + max_new + 63) // 32 + 1,
            seed=seed)

    rng = np.random.default_rng(0)
    shared = list(rng.integers(0, 256, size=prompt_len))
    prompts = [shared + list(rng.integers(0, 256, size=4 + i))
               for i in range(n_requests)]

    def run_arm(engine, reuse_prefix=False):
        # sequential requests: TTFT is the number this bench exists
        # to move, and queueing other requests would pollute it
        if reuse_prefix:
            # seed the prefix cache OUTSIDE the timed window — its
            # tokens don't count, so its wall time must not either
            engine.decode(prompts[0], max_new, timeout=600.0)
        ttfts, boundaries = [], []
        t0 = time.perf_counter()
        n_tokens = 0
        for prompt in prompts:
            req = engine.submit(prompt, max_new)
            t_sub = time.perf_counter()
            stream = req.tokens(timeout=600.0)
            next(stream)
            ttfts.append(time.perf_counter() - t_sub)
            n_tokens += 1 + sum(1 for _ in stream)
            boundaries.append(req.ttft_boundaries)
        wall = time.perf_counter() - t0
        engine.close()
        lat = np.asarray(ttfts) * 1e3
        return {
            "tokens_per_s": round(n_tokens / wall, 1),
            "ttft_p50_ms": round(float(np.percentile(lat, 50)), 2),
            "ttft_p99_ms": round(float(np.percentile(lat, 99)), 2),
            "ttft_boundaries_p50": int(np.median(boundaries)),
        }

    arms = {}
    arms["plain"] = run_arm(DecodeEngine(mk(), name="b-plain").warmup())
    arms["chunked"] = run_arm(
        DecodeEngine(mk(), name="b-chunk", chunk=64).warmup())
    arms["prefix_hit"] = run_arm(
        DecodeEngine(mk(), name="b-prefix", chunk=64,
                     prefix_cache=True).warmup(),
        reuse_prefix=True)
    draft = TransformerDecodeModel.init(
        vocab=256, hidden=32, n_layers=1, n_heads=2,
        max_len=prompt_len + max_new + 64, max_slots=4, page=32,
        max_pages_per_slot=(prompt_len + max_new + 63) // 32 + 1,
        seed=5)
    arms["speculative"] = run_arm(
        DecodeEngine(mk(), name="b-spec", chunk=64, prefix_cache=True,
                     speculative=SpeculativeConfig(draft=draft, k=4))
        .warmup(), reuse_prefix=True)
    return {
        "metric": "decode_tokens_per_s",
        "value": arms["plain"]["tokens_per_s"],
        "unit": "tokens/s",
        "vs_baseline": None,
        "host_bound": _host_bound(),
        "arms": arms,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "note": (f"v2 decode arms on a tiny {prompt_len}-token-prompt "
                 "transformer pair; headline value = plain-arm "
                 "tokens/s (benchdiff: higher is better; ttft_*_ms "
                 "and boundary counts are informational — chunked/"
                 "prefix/speculative arms should dominate plain on "
                 "TTFT boundaries everywhere). CAVEAT: CPU row is "
                 "host-bound (dispatch overhead ~ kernel time at "
                 "this model size) — re-record on chip "
                 "(`python bench.py --only decode`)"),
    }


def bench_health_overhead(steps=80, repeats=3):
    """ISSUE 3 smoke: per-step cost of the in-step health stats + host
    publication. Three modes on the SAME architecture (fresh net each,
    jit warmed outside the timed region): health on (telemetry enabled),
    health off (`telemetry.health.configure(enabled=False)` — the stats
    are compiled out of the step), telemetry disabled entirely.
    Acceptance: on-vs-off overhead <= 10%."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.telemetry import health

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 256)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)]

    def build():
        conf = (NeuralNetConfiguration.Builder().seed(11)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer.Builder().nIn(256).nOut(256)
                       .activation("relu").build())
                .layer(DenseLayer.Builder().nOut(256)
                       .activation("relu").build())
                .layer(DenseLayer.Builder().nOut(256)
                       .activation("relu").build())
                .layer(OutputLayer.Builder().nOut(10)
                       .activation("softmax")
                       .lossFunction(LossFunction.MCXENT).build())
                .build())
        return MultiLayerNetwork(conf).init()

    def time_mode(setup, teardown):
        setup()
        try:
            net = build()
            net.fit([(X, y)] * 5)                 # compile + settle
            best = float("inf")
            for _ in range(repeats):
                t0 = time.perf_counter()
                net.fit([(X, y)] * steps)
                _ = float(np.asarray(net._params[0]["W"]).sum())  # sync
                best = min(best, time.perf_counter() - t0)
            return best / steps * 1e3             # ms/step
        finally:
            teardown()

    was_enabled = telemetry.enabled()
    on_ms = time_mode(telemetry.enable, lambda: None)
    off_ms = time_mode(lambda: health.configure(enabled=False),
                       lambda: health.configure(enabled=True))
    dis_ms = time_mode(telemetry.disable,
                       telemetry.enable if was_enabled
                       else (lambda: None))
    overhead_pct = (on_ms - off_ms) / off_ms * 100.0
    return {
        "metric": "health_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "%",
        "vs_baseline": None,
        "step_ms_health_on": round(on_ms, 4),
        "step_ms_health_off": round(off_ms, 4),
        "step_ms_telemetry_disabled": round(dis_ms, 4),
        "steps": steps,
        "note": ("min-of-3 mean step time over {n} steps of a 4-layer "
                 "256-wide MLP, batch 128; health on = per-layer fused "
                 "stats in-step + one-behind host publication; off = "
                 "stats compiled out; disabled = no telemetry at "
                 "all".format(n=steps)),
    }


def bench_precision(steps=60, repeats=3, n_requests=200):
    """ISSUE 4 smoke: (a) fp32 vs bf16_mixed steady-state step time on
    the same 4-layer MLP (master weights fp32 in both; the mixed run
    adds the compute casts + the in-step loss scaler), and (b) int8-PTQ
    vs fp32 serving p50/p99 through the DynamicBatcher on a warmed AOT
    ladder. On TPU the bf16/int8 rows are the MXU payoff; on CPU they
    mainly demonstrate the overhead side (bf16 is emulated), which is
    why off-TPU rows land platform-suffixed in BENCH_ALL.json."""
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.precision import quantize
    from deeplearning4j_tpu.serving import BucketLadder, InferenceSession

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 256)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)]

    def build(precision=None):
        b = (NeuralNetConfiguration.Builder().seed(11).updater(Adam(1e-3)))
        if precision:
            b = b.precision(precision)
        conf = (b.list()
                .layer(DenseLayer.Builder().nIn(256).nOut(256)
                       .activation("relu").build())
                .layer(DenseLayer.Builder().nOut(256)
                       .activation("relu").build())
                .layer(DenseLayer.Builder().nOut(256)
                       .activation("relu").build())
                .layer(OutputLayer.Builder().nOut(10)
                       .activation("softmax")
                       .lossFunction(LossFunction.MCXENT).build())
                .build())
        return MultiLayerNetwork(conf).init()

    def step_ms(precision):
        net = build(precision)
        net.fit([(X, y)] * 5)                     # compile + settle
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            net.fit([(X, y)] * steps)
            _ = float(np.asarray(net._params[0]["W"]).sum())   # sync
            best = min(best, time.perf_counter() - t0)
        return best / steps * 1e3

    fp32_ms = step_ms(None)
    bf16_ms = step_ms("bf16_mixed")

    # serving: fp32 servable vs int8 PTQ of the SAME trained net
    net = build(None)
    net.fit([(X, y)] * 10)
    calib = [X[i * 32:(i + 1) * 32] for i in range(4)]
    qsv = quantize(net, calib, example_shape=(256,))

    def percentiles(session, name, x, n):
        for _ in range(10):
            session.predict(name, x)
        lat = np.empty(n)
        for i in range(n):
            t0 = time.perf_counter()
            session.predict(name, x)
            lat[i] = time.perf_counter() - t0
        return np.percentile(lat * 1e3, [50, 99])

    x1 = X[0]
    with InferenceSession(max_latency=0.001) as session:
        ladder = BucketLadder((1, 8, 32))
        session.register("fp32", net, example_shape=(256,), ladder=ladder,
                         warmup=True)
        session.register("int8", qsv, ladder=ladder, warmup=True)
        p50_f, p99_f = percentiles(session, "fp32", x1, n_requests)
        p50_q, p99_q = percentiles(session, "int8", x1, n_requests)

    return {
        "metric": "precision_bf16_vs_fp32_step_ratio",
        "value": round(bf16_ms / fp32_ms, 4),
        "unit": "x (bf16_mixed/fp32 step time; <1 is a speedup)",
        "vs_baseline": None,
        "host_bound": _host_bound(),
        "step_ms_fp32": round(fp32_ms, 4),
        "step_ms_bf16_mixed": round(bf16_ms, 4),
        "serving_p50_ms_fp32": round(float(p50_f), 3),
        "serving_p99_ms_fp32": round(float(p99_f), 3),
        "serving_p50_ms_int8": round(float(p50_q), 3),
        "serving_p99_ms_int8": round(float(p99_q), 3),
        "ptq_calibration_max_err": qsv.calibration_max_err,
        "steps": steps,
        "note": ("4-layer 256-wide MLP batch 128; bf16_mixed = fp32 "
                 "master + bf16 compute + dynamic loss scaling compiled "
                 "into the step; serving p50/p99 at batch 1 through the "
                 "DynamicBatcher on a warmed (1,8,32) ladder (includes "
                 "the 1 ms coalescing window)"),
    }


def bench_resilience(steps_per_epoch=10, epochs=4, every=2):
    """ISSUE 5 smoke: per-step overhead of checkpointing every `every`
    iterations, sync vs async, against a no-checkpoint baseline on the
    same MNIST-scale MLP (784-256-256-10, batch 128). The async row's
    step overhead is the device-side snapshot stall; the sync row eats
    the full serialize+write on the loop. Also reports the measured
    per-checkpoint stall vs write cost (acceptance: stall <= 10% of the
    write cost — the same instruments the tier-1 test asserts on)."""
    import tempfile

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.nn import (
        DenseLayer, InputType, MultiLayerNetwork, NeuralNetConfiguration,
        OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam
    from deeplearning4j_tpu.parallel import ElasticTrainer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(128, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 128)]
    data = [(X, y)] * steps_per_epoch

    def build():
        conf = (NeuralNetConfiguration.Builder().seed(5)
                .updater(Adam(1e-3)).list()
                .layer(DenseLayer.Builder(nOut=256, activation="relu")
                       .build())
                .layer(DenseLayer.Builder(nOut=256, activation="relu")
                       .build())
                .layer(OutputLayer.Builder().nOut(10)
                       .activation("softmax").build())
                .setInputType(InputType.feedForward(784))
                .build())
        return MultiLayerNetwork(conf).init()

    def step_ms(mode, repeats=3):
        net = build()
        if mode == "none":
            fit, cleanup = (lambda e: net.fit(data, e)), (lambda: None)
        else:
            d = tempfile.mkdtemp(prefix=f"bench_ckpt_{mode}_")
            tr = ElasticTrainer(net, d, everyNIterations=every,
                                keepLast=2, asyncSave=(mode == "async"))

            def cleanup(tr=tr, d=d):
                import shutil

                tr.close()
                shutil.rmtree(d, ignore_errors=True)

            # ElasticTrainer.fit treats epochs as the TOTAL budget, so
            # each timed repeat must raise the budget to train again
            fit = lambda e, tr=tr: tr.fit(data, epochs=e)  # noqa: E731
        budget = 1
        fit(budget)             # compile train step + cloner + writer
        # steady state only: the warm pass's one-time cloner compile
        # must not pollute the snapshot-stall histogram
        telemetry.get_registry().reset()
        best = float("inf")
        for _ in range(repeats):
            budget += epochs
            t0 = time.perf_counter()
            fit(budget if mode != "none" else epochs)
            _ = float(np.asarray(net._params[0]["W"]).sum())
            best = min(best, time.perf_counter() - t0)
        cleanup()
        return best / (steps_per_epoch * epochs) * 1e3

    none_ms = step_ms("none")
    sync_ms = step_ms("sync")
    telemetry.get_registry().reset()
    async_ms = step_ms("async")
    reg = telemetry.get_registry()
    snap = reg.histogram("dl4j_ckpt_snapshot_seconds")
    write = reg.histogram("dl4j_ckpt_write_seconds", labelnames=("mode",))
    aw = write.labels(mode="async")
    stall_ms = snap.sum / max(snap.count, 1) * 1e3
    write_ms = aw.sum / max(aw.count, 1) * 1e3
    return {
        "metric": "resilience_ckpt_async_vs_sync_step_overhead",
        "value": round((async_ms - none_ms) / none_ms * 100.0, 2),
        "unit": "% step overhead (async checkpointing vs no checkpoints)",
        "vs_baseline": None,
        "step_ms_no_ckpt": round(none_ms, 4),
        "step_ms_sync_ckpt": round(sync_ms, 4),
        "step_ms_async_ckpt": round(async_ms, 4),
        "sync_overhead_pct": round((sync_ms - none_ms) / none_ms * 100.0,
                                   2),
        "snapshot_stall_ms": round(stall_ms, 4),
        "async_write_ms": round(write_ms, 4),
        "stall_over_write": round(stall_ms / max(write_ms, 1e-9), 4),
        "ckpt_every_n_steps": every,
        "note": ("MNIST-scale MLP (784-256-256-10, batch 128), "
                 f"checkpoint every {every} steps; async pays only the "
                 "device-side snapshot clone on the loop (acceptance: "
                 "stall <= 10% of write cost)"),
    }


def bench_trace_overhead(steps_per_epoch=8, epochs=30, trials=5,
                         n_requests=150):
    """ISSUE 10: what the tracing subsystem costs on the hot paths.

    Same MLP fit loop and same serving path under four modes:
    tracing sampled-ON (rate 1.0: every step/request builds spans),
    sampled-OFF (rate 0: the head sampler declines, per-step cost is a
    falsy-context check), tracing DISABLED (telemetry on, tracing
    compiled out — the pre-PR-10 path), and full telemetry.disable()
    for context. Steps/s are best-of-``trials`` (min wall time), which
    is the standard way to see a <=1% effect through this container's
    scheduler jitter. Acceptance: sampled-off steps/s within 1% of
    tracing-disabled."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import BucketLadder, InferenceSession
    from deeplearning4j_tpu.telemetry import tracing

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(128).nOut(256)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(10).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(64, 128)).astype(np.float32),
                np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)])
               for _ in range(steps_per_epoch)]
    session = InferenceSession(max_latency=0.001)
    session.register("trace_bench", net, example_shape=(128,),
                     ladder=BucketLadder((1, 8)), warmup=True)
    x1 = rng.normal(size=(128,)).astype(np.float32)

    modes = {
        "sampled_on": lambda: (telemetry.enable(),
                               tracing.configure(enabled=True,
                                                 sample_rate=1.0)),
        "sampled_off": lambda: (telemetry.enable(),
                                tracing.configure(enabled=True,
                                                  sample_rate=0.0)),
        "tracing_disabled": lambda: (telemetry.enable(),
                                     tracing.configure(enabled=False)),
        "telemetry_disabled": lambda: (telemetry.disable(),),
    }

    def traced_predict():
        # a bare session.predict has no ambient trace, so it would
        # measure zero tracing work in EVERY mode — give each request
        # the root an HTTP handler would have opened (start_trace
        # applies this mode's sampler: spans in sampled_on, None in
        # the off/disabled modes)
        root = tracing.start_trace("bench.predict")
        with (root or tracing.NULL):
            session.predict("trace_bench", x1)

    best_s = {m: float("inf") for m in modes}
    lats = {m: [] for m in modes}

    def measure(mode, arm):
        arm()
        t0 = time.perf_counter()
        net.fit(batches, epochs)
        best_s[mode] = min(best_s[mode], time.perf_counter() - t0)
        for _ in range(5):
            traced_predict()
        lat = np.empty(n_requests // trials + 1)
        for i in range(len(lat)):
            t0 = time.perf_counter()
            traced_predict()
            lat[i] = time.perf_counter() - t0
        lats[mode].append(lat)

    tracing_modes = {m: modes[m] for m in
                     ("sampled_on", "sampled_off", "tracing_disabled")}
    try:
        telemetry.enable()
        net.fit(batches, 2)           # warm the telemetry-on step plan
        # INTERLEAVED rounds over the three tracing modes: a <=1%
        # effect is smaller than this container's minute-scale load
        # drift, so back-to-back per-mode blocks alias drift into the
        # comparison; cycling modes inside each round puts every mode
        # under the same drift. All three share one health build plan,
        # so switching costs no step recompile — telemetry_disabled
        # does NOT (its plan compiles health out), so it runs as its
        # own sequential block below (context only, not part of the
        # acceptance comparison).
        for _ in range(trials):
            for mode, arm in tracing_modes.items():
                measure(mode, arm)
        modes["telemetry_disabled"]()
        net.fit(batches, 2)           # warm the disabled step plan
        for _ in range(trials):
            measure("telemetry_disabled", modes["telemetry_disabled"])
    finally:
        telemetry.enable()
        tracing.configure(enabled=True, sample_rate=0.01)
        session.close()
    steps_s, p50_ms, p99_ms = {}, {}, {}
    for mode in modes:
        steps_s[mode] = round(steps_per_epoch * epochs / best_s[mode], 1)
        p50, p99 = np.percentile(np.concatenate(lats[mode]) * 1e3,
                                 [50, 99])
        p50_ms[mode] = round(float(p50), 3)
        p99_ms[mode] = round(float(p99), 3)
    off_pct = 100.0 * (steps_s["tracing_disabled"]
                       - steps_s["sampled_off"]) / \
        steps_s["tracing_disabled"]
    return {
        "metric": "trace_overhead_sampled_off_pct",
        "value": round(off_pct, 2),
        "unit": "%",
        "vs_baseline": None,
        "steps_per_s": steps_s,
        "serving_p50_ms": p50_ms,
        "serving_p99_ms": p99_ms,
        "steps_per_trial": steps_per_epoch * epochs,
        "trials": trials,
        "note": ("MLP 128-256-10 batch 64 fit loop + single-client "
                 "serving predicts; value = sampled-off steps/s deficit "
                 "vs tracing-disabled (acceptance <= 1%); sampled-on "
                 "pays span construction every step/request"),
    }


def bench_profile(steps_per_epoch=8, epochs=30, trials=5,
                  n_requests=150, load_seconds=3.0):
    """ISSUE 18: what the continuous profiler costs, and whether it
    attributes.

    Three measurements: (1) sampler-ON vs sampler-OFF paired fit +
    predict overhead — INTERLEAVED rounds (like trace_overhead: a <=1%
    effect is smaller than this container's minute-scale load drift,
    so every mode must sit under the same drift), best-of-``trials``
    min wall time, acceptance <= 1%; (2) a profile taken under a real
    serving load must attribute >= 90% of samples to named
    (non-``other``) subsystems; (3) the wall cost of one on-demand
    deep capture."""
    import threading

    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import BucketLadder, InferenceSession
    from deeplearning4j_tpu.telemetry import profiler

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(128).nOut(256)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(10).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(64, 128)).astype(np.float32),
                np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)])
               for _ in range(steps_per_epoch)]
    session = InferenceSession(max_latency=0.001)
    session.register("profile_bench", net, example_shape=(128,),
                     ladder=BucketLadder((1, 8)), warmup=True)
    x1 = rng.normal(size=(128,)).astype(np.float32)

    telemetry.enable()
    profiler.configure(hz=19.0)
    modes = {
        "sampler_on": lambda: profiler.start(),
        "sampler_off": lambda: profiler.stop(),
    }
    best_s = {m: float("inf") for m in modes}
    lats = {m: [] for m in modes}

    def measure(mode, arm):
        arm()
        t0 = time.perf_counter()
        net.fit(batches, epochs)
        best_s[mode] = min(best_s[mode], time.perf_counter() - t0)
        for _ in range(5):
            session.predict("profile_bench", x1)
        lat = np.empty(n_requests // trials + 1)
        for i in range(len(lat)):
            t0 = time.perf_counter()
            session.predict("profile_bench", x1)
            lat[i] = time.perf_counter() - t0
        lats[mode].append(lat)

    att = {}
    capture_wall = 0.0
    capture_meta = {}
    try:
        net.fit(batches, 2)           # warm the step plan
        session.predict("profile_bench", x1)
        for _ in range(trials):
            for mode, arm in modes.items():
                measure(mode, arm)
        # (2) attribution under a real serving load: hammer threads +
        # the main thread drive predict while the sampler runs — the
        # batcher coalescer / replica workers attribute by thread
        # name, the client threads by module-path heuristics
        profiler.clear()
        profiler.start()
        stop_evt = threading.Event()

        def hammer():
            while not stop_evt.is_set():
                session.predict("profile_bench", x1)

        clients = [threading.Thread(target=hammer, daemon=True,
                                    name=f"profile-bench-client-{i}")
                   for i in range(3)]
        for c in clients:
            c.start()
        t_end = time.perf_counter() + load_seconds
        while time.perf_counter() < t_end:
            session.predict("profile_bench", x1)
        stop_evt.set()
        for c in clients:
            c.join(timeout=5.0)
        att = profiler.describe()["attribution"]
        profiler.stop()
        # (3) deep-capture cost (device trace included when the
        # backend supports it; its wall cost ~= the requested window)
        import tempfile
        t0 = time.perf_counter()
        capture_meta = profiler.capture(
            seconds=0.5, out_dir=tempfile.mkdtemp(prefix="dl4j-bench-"))
        capture_wall = time.perf_counter() - t0
    finally:
        profiler.stop()
        session.close()
    steps_s, p50_ms, p99_ms = {}, {}, {}
    for mode in modes:
        steps_s[mode] = round(steps_per_epoch * epochs / best_s[mode], 1)
        p50, p99 = np.percentile(np.concatenate(lats[mode]) * 1e3,
                                 [50, 99])
        p50_ms[mode] = round(float(p50), 3)
        p99_ms[mode] = round(float(p99), 3)
    overhead_pct = 100.0 * (steps_s["sampler_off"]
                            - steps_s["sampler_on"]) / \
        steps_s["sampler_off"]
    total = sum(att.values()) or 1
    non_other = 1.0 - att.get("other", 0) / total
    return {
        "metric": "profile_sampler_overhead_pct",
        "value": round(overhead_pct, 2),
        "unit": "%",
        "vs_baseline": None,
        "steps_per_s": steps_s,
        "serving_p50_ms": p50_ms,
        "serving_p99_ms": p99_ms,
        "attribution_non_other_fraction": round(non_other, 4),
        "attribution": att,
        "capture_wall_s": round(capture_wall, 3),
        "capture_samples": capture_meta.get("samples"),
        "capture_device_trace": capture_meta.get("device_trace"),
        "steps_per_trial": steps_per_epoch * epochs,
        "trials": trials,
        "note": ("MLP 128-256-10 batch 64 fit loop + serving predicts; "
                 "value = sampler-on steps/s deficit vs sampler-off at "
                 "19Hz (acceptance <= 1%); attribution fraction from a "
                 f"{load_seconds:.0f}s serving-load profile (acceptance "
                 ">= 0.9 non-other); capture cost is one 0.5s deep "
                 "capture incl. device trace"),
    }


def bench_compile_ledger(steps_per_epoch=8, epochs=10, rounds=20):
    """ISSUE 11: what the compile ledger + HLO audit cost on the hot
    paths.

    The ONLY per-step difference between ledger-on and ledger-off is
    the loops' ``compile_ledger.note_step`` call (steady state: one
    thread-local read), so the headline is measured where it is
    actually measurable: the note_step seam is microbenchmarked
    exactly as the fit loop invokes it (same arg tuple, policy label,
    window) and reported as a percentage of the fit loop's measured
    median step time. A whole-fit on/off differential is ALSO recorded
    (paired back-to-back rounds, order alternated, median ratio) as
    ``fit_paired_median_pct`` — context only: this container's
    wall-clock jitter (±1.5% between adjacent 0.1 s windows) dwarfs a
    sub-0.1% effect, which is precisely why the seam measurement is
    the acceptance number (<= 1%). One warmup ladder is also timed
    with the audit on vs off — the eager as_text+parse cost per AOT
    bucket, paid at warmup (never on the request path)."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.serving import BucketLadder, ModelRegistry
    from deeplearning4j_tpu.telemetry import compile_ledger

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(128).nOut(256)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(10).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(64, 128)).astype(np.float32),
                np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)])
               for _ in range(steps_per_epoch)]

    modes = {
        "ledger_on": lambda: (telemetry.enable(),
                              compile_ledger.configure(enabled=True)),
        "ledger_off": lambda: (telemetry.enable(),
                               compile_ledger.configure(enabled=False)),
        "telemetry_disabled": lambda: (telemetry.disable(),),
    }
    walls = {m: [] for m in modes}

    def measure(mode):
        modes[mode]()
        t0 = time.perf_counter()
        net.fit(batches, epochs)
        dt = time.perf_counter() - t0
        walls[mode].append(dt)
        return dt

    def warm_ladder(audit_on):
        compile_ledger.configure(enabled=audit_on)
        reg = ModelRegistry()
        t0 = time.perf_counter()
        # a fresh registration AOT-compiles the whole ladder (jax's
        # AOT cache makes repeats cheap, so the FIRST arm pays the
        # backend compiles — run audit-off first so the audit arm
        # isolates as_text+parse+ledger, not XLA)
        reg.register(f"ledger_bench_{int(audit_on)}", net,
                     example_shape=(128,),
                     ladder=BucketLadder((1, 8, 64)), warmup=True)
        return time.perf_counter() - t0

    ratios = []
    try:
        telemetry.enable()
        net.fit(batches, 2)            # warm the step executable
        for i in range(rounds):
            on_first = i % 2 == 0      # alternate order per round
            first, second = (("ledger_on", "ledger_off") if on_first
                             else ("ledger_off", "ledger_on"))
            t_first = measure(first)
            t_second = measure(second)
            t_on, t_off = ((t_first, t_second) if on_first
                           else (t_second, t_first))
            ratios.append(t_on / t_off)
        modes["telemetry_disabled"]()
        net.fit(batches, 2)            # warm the disabled step plan
        for _ in range(rounds // 4):
            measure("telemetry_disabled")
        telemetry.enable()
        warm_off = warm_ladder(False)
        warm_on = warm_ladder(True)
        records = len(compile_ledger.get_ledger().describe())
    finally:
        telemetry.enable()
        compile_ledger.configure(enabled=True)
    ratios.sort()
    median_ratio = ratios[len(ratios) // 2]
    steps_s = {m: round(steps_per_epoch * epochs / min(walls[m]), 1)
               for m in modes}

    # the seam itself, measured as the fit loop calls it: steady-state
    # note_step against a warmed site (one thread-local read)
    from deeplearning4j_tpu.telemetry import compile_ledger as _cl

    _cl.configure(enabled=True)
    telemetry.enable()
    import jax as _jax

    step_fn = net._train_step
    f0, l0 = batches[0]
    lmask0 = np.ones((f0.shape[0],), np.float32)
    note_args = (net._params, net._states, net._opt_states,
                 net._prec_state, f0, l0, lmask0,
                 _jax.random.key(0), 0)
    _cl.note_step("bench_seam", step_fn, note_args)   # warm the path
    n_calls = 50_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        _cl.note_step("bench_seam", step_fn, note_args,
                      policy="float32/h10", window=(0.0, 1.0))
    note_us = (time.perf_counter() - t0) / n_calls * 1e6
    median_step_s = sorted(walls["ledger_on"])[
        len(walls["ledger_on"]) // 2] / (steps_per_epoch * epochs)
    seam_pct = 100.0 * (note_us * 1e-6) / median_step_s
    return {
        "metric": "compile_ledger_overhead_pct",
        "value": round(seam_pct, 3),
        "unit": "%",
        "vs_baseline": None,
        "note_step_us": round(note_us, 2),
        "median_step_ms": round(median_step_s * 1e3, 3),
        "fit_paired_median_pct": round(100.0 * (median_ratio - 1.0), 2),
        "steps_per_s": steps_s,
        "warmup_audit_on_s": round(warm_on, 4),
        "warmup_audit_off_s": round(warm_off, 4),
        "ledger_records": records,
        "steps_per_round": steps_per_epoch * epochs,
        "rounds": rounds,
        "note": ("MLP 128-256-10 batch 64 fit loop; value = measured "
                 "steady-state note_step seam cost (the ONLY per-step "
                 "ledger-on/off difference) as % of the measured "
                 "median step time (acceptance <= 1%). "
                 "fit_paired_median_pct is the whole-fit paired-round "
                 "differential — context only, dominated by ±1.5% "
                 "container wall jitter. warmup_audit_*_s: a 3-bucket "
                 "AOT ladder warmup with the eager HLO audit on vs off "
                 "(audit cost is paid at warmup, never per request)"),
    }


def bench_memory(steps_per_epoch=8, epochs=10, rounds=12,
                 census_trials=20):
    """ISSUE 14: what the HBM ownership ledger costs on the hot path.

    The ONLY per-step difference between ledger-on and ledger-off is
    the loops' ``Claim.touch()`` (one dict read + one gauge set), so —
    exactly like the compile-ledger row — the headline is the touch
    seam microbenchmarked as the fit loop invokes it, reported as a
    percentage of the measured median step time (acceptance <= 1%). A
    whole-fit paired differential — ledger on vs off with the REST of
    telemetry held constant (``memledger.configure(enabled=)``, the
    compile-ledger isolation pattern) — rides along as context
    (dominated by this container's ±1.5% wall jitter), a
    telemetry-disabled block anchors the absolute floor, and the
    census cost (the /metrics-scrape-time claims-vs-device
    reconciliation, incl. the live-array fallback walk on CPU) is
    timed separately — it is a scrape cost, never a step cost."""
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.telemetry import memledger

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(128).nOut(256)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(10).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    batches = [(rng.normal(size=(64, 128)).astype(np.float32),
                np.eye(10, dtype=np.float32)[rng.integers(0, 10, 64)])
               for _ in range(steps_per_epoch)]

    modes = {
        "ledger_on": lambda: (telemetry.enable(),
                              memledger.configure(enabled=True)),
        "ledger_off": lambda: (telemetry.enable(),
                               memledger.configure(enabled=False)),
        "telemetry_disabled": lambda: (telemetry.disable(),),
    }
    walls = {m: [] for m in modes}

    def measure(mode):
        modes[mode]()
        t0 = time.perf_counter()
        net.fit(batches, epochs)
        dt = time.perf_counter() - t0
        walls[mode].append(dt)
        return dt

    ratios = []
    try:
        telemetry.enable()
        net.fit(batches, 2)             # warm the instrumented plan
        for i in range(rounds):
            on_first = i % 2 == 0       # alternate order per round
            first, second = (("ledger_on", "ledger_off") if on_first
                             else ("ledger_off", "ledger_on"))
            t_first = measure(first)
            t_second = measure(second)
            t_on, t_off = ((t_first, t_second) if on_first
                           else (t_second, t_first))
            ratios.append(t_on / t_off)
        modes["telemetry_disabled"]()
        net.fit(batches, 2)             # warm the disabled plan
        for _ in range(max(1, rounds // 4)):
            measure("telemetry_disabled")
    finally:
        telemetry.enable()
        memledger.configure(enabled=True)

    # the seam itself, measured as the fit loop calls it: one running-
    # total read + one gauge set against the live train claim
    mem = memledger.claim(
        "train", "bench_seam",
        tree={"p": net._params, "o": net._opt_states})
    mem.touch()                          # warm the path
    n_calls = 50_000
    t0 = time.perf_counter()
    for _ in range(n_calls):
        mem.touch()
    touch_us = (time.perf_counter() - t0) / n_calls * 1e6
    mem.release()

    census_walls = []
    for _ in range(census_trials):
        t0 = time.perf_counter()
        memledger.census()
        census_walls.append(time.perf_counter() - t0)
    census_walls.sort()

    ratios.sort()
    median_ratio = ratios[len(ratios) // 2]
    median_step_s = sorted(walls["ledger_on"])[
        len(walls["ledger_on"]) // 2] / (steps_per_epoch * epochs)
    seam_pct = 100.0 * (touch_us * 1e-6) / median_step_s
    steps_s = {m: round(steps_per_epoch * epochs / min(walls[m]), 1)
               for m in modes}
    n_claims = len(memledger.get_memledger().claims())
    return {
        "metric": "memory_ledger_overhead_pct",
        "value": round(seam_pct, 3),
        "unit": "%",
        "vs_baseline": None,
        "touch_us": round(touch_us, 3),
        "median_step_ms": round(median_step_s * 1e3, 3),
        "fit_paired_median_pct": round(100.0 * (median_ratio - 1.0), 2),
        "census_median_ms": round(
            census_walls[len(census_walls) // 2] * 1e3, 3),
        "census_claims": n_claims,
        "steps_per_s": steps_s,
        "steps_per_round": steps_per_epoch * epochs,
        "rounds": rounds,
        "note": ("MLP 128-256-10 batch 64 fit loop; value = measured "
                 "steady-state Claim.touch() seam cost (the ONLY "
                 "per-step ledger-on/off difference) as % of the "
                 "measured median step time (acceptance <= 1%). "
                 "fit_paired_median_pct is the whole-fit paired-round "
                 "ledger-on-vs-off differential with the rest of "
                 "telemetry held constant — context only, dominated "
                 "by ±1.5% container wall jitter; the "
                 "telemetry_disabled block anchors the absolute "
                 "floor. census_median_ms is the /metrics scrape-time "
                 "reconciliation (live-array fallback walk on this "
                 "CPU host — memory_stats() path on chip is cheaper), "
                 "never paid per step"),
    }


def bench_coldstart():
    """ISSUE 13: cold vs warm process start through the persistent
    executable store (tools/coldstart.py). Every trial is a REAL
    subprocess restart: a 3-bucket serving registration and a
    Supervisor kill-and-resume, each cold (empty store) then warm.
    Zero-compile warm starts are ledger-asserted (causes all
    cache_hit), not inferred from timing."""
    import pathlib
    import sys as _sys

    tools = str(pathlib.Path(__file__).resolve().parent / "tools")
    if tools not in _sys.path:
        _sys.path.insert(0, tools)
    import coldstart

    report = coldstart.run_report()
    s, r = report["serving"], report["resume"]
    return {
        "metric": "coldstart_warm_registration_seconds",
        "value": s["warm"]["register_seconds"],
        "unit": "s",
        "vs_baseline": None,
        # the children run on the host platform regardless of the
        # parent's backend (a bench parent holding the chip cannot
        # hand it to 5 subprocesses), so the row is pinned to cpu and
        # is host-bound by construction: compile/deserialize walls
        # scale with host CPU + filesystem, not the model math
        "platform": "cpu",
        "host_bound": True,
        "serving_cold_s": s["cold"]["register_seconds"],
        "serving_warm_s": s["warm"]["register_seconds"],
        "serving_speedup_x": s["speedup"],
        "serving_warm_compiles": s["warm"]["compiles"],
        "serving_warm_causes": s["warm"]["causes"],
        "resume_cold_s": r["cold"]["resume_seconds"],
        "resume_warm_s": r["warm"]["resume_seconds"],
        "resume_speedup_x": r["speedup"],
        "resume_warm_compiles": r["warm"]["compiles"],
        "resume_warm_fit_causes": r["warm"]["fit_causes"],
        "resume_params_bit_identical":
            r["warm"]["params_sha"] == r["cold"]["params_sha"],
        "store_entries": len(report["store_contents"]),
        "store_bytes": sum(e["bytes"]
                           for e in report["store_contents"]),
        "note": ("subprocess-measured (fresh interpreter per trial): "
                 "8x384 MLP, (1,8,32) serving ladder, 2-epoch "
                 "supervised fit killed after epoch 1. Acceptance: "
                 "warm registration >= 5x faster than cold AND zero "
                 "XLA compiles warm (ledger causes all cache_hit). "
                 "Resume wall includes checkpoint restore + weight-"
                 "init compiles, so its ratio is structurally "
                 "smaller; the step acquisition itself shrinks from "
                 "a >1s compile to a ~15ms deserialize"),
    }


def bench_fleet(duration=1.2, deadline_ms=100.0, rows_per_request=1):
    """ISSUE 15: the fleet-router hop, measured (the PAPERS.md
    off-math-path rule: once kernels are fast, the extra network hop
    is where throughput goes to die — so the router is benched against
    a ~free host-side model, making the router itself the number).

    Three phases, all open-loop (fixed arrival schedule — a closed
    loop would back off exactly when the router struggles):

    - 1-worker vs 3-worker saturation: offered QPS swept geometrically;
      "saturation" is the max completed-rows/s whose completion ratio
      stays >= 90% with every answer inside `deadline_ms`;
    - rollout-in-progress p99: the 3-worker fleet at ~half saturation
      with a canary rollout mirroring 25% of traffic, vs the same load
      with no rollout — the canary tax on client latency (mirrors ride
      a background thread, so the tax should be ~the pin rewrite);
    - router hop overhead: direct-to-worker vs through-router p50 at
      light load, decomposed into the ISSUE 16 hop phases
      (queue/execute/worker_other/transit from the workers'
      Server-Timing headers) whose means must cover >=90% of the
      router-hop mean;
    - SLO-evaluation overhead: one time-series sample + burn-rate
      evaluation over the populated registry, amortized per request at
      the default sampling interval — must stay <=1% of request cost.
    """
    import threading
    from deeplearning4j_tpu import telemetry
    from deeplearning4j_tpu.fleet.router import (
        FleetRouter, TransportFailure, _http, spawn_local_workers)
    from deeplearning4j_tpu.telemetry import slo as slo_mod
    from deeplearning4j_tpu.telemetry import timeseries

    # the worker is made the bottleneck ON PURPOSE (20ms serial
    # service, ladder pinned to batch-1 so the batcher cannot coalesce
    # it away): per-worker capacity is exactly 50 rows/s, so the
    # 1-vs-3-worker sweep measures the router's scale-out, not this
    # container's 2-core ceiling (which a ~free model hits at ~200
    # req/s of client+router+worker HTTP work combined)
    spec = {"models": [{"name": "m", "version": 1, "kind": "linear",
                        "scale": 2.0, "delay_ms": 20.0,
                        "example_shape": [8], "ladder": [1]}]}
    body = json.dumps(
        {"instances": [[1.0] * 8] * rows_per_request}).encode()
    deadline_s = deadline_ms / 1e3

    def open_loop(url, qps, run_s):
        lats, failures = [], [0]
        threads = []
        start = time.perf_counter()
        t_next = start

        def fire():
            t0 = time.perf_counter()
            try:
                status, _, _ = _http(
                    url + "/serving/v1/models/m:predict", body=body,
                    timeout=10.0)
            except TransportFailure:
                failures[0] += 1
                return
            dt = time.perf_counter() - t0
            if status == 200 and dt <= deadline_s:
                lats.append(dt)
            else:
                failures[0] += 1

        while t_next < start + run_s:
            now = time.perf_counter()
            if t_next > now:
                time.sleep(t_next - now)
            t = threading.Thread(target=fire, daemon=True)
            t.start()
            threads.append(t)
            t_next += 1.0 / qps
        for t in threads:
            t.join(15.0)
        offered = len(threads)
        lat = np.sort(np.asarray(lats)) if lats else np.zeros(1)
        return {
            "offered_qps": qps, "offered": offered,
            "completed": len(lats),
            "completed_rows_per_s": round(
                len(lats) * rows_per_request / run_s, 1),
            "completion_ratio": round(len(lats) / max(offered, 1), 3),
            "p50_ms": round(float(lat[len(lat) // 2]) * 1e3, 2),
            "p99_ms": round(float(lat[int(len(lat) * 0.99)]) * 1e3, 2),
        }

    def saturation_sweep(url):
        points, best = [], 0.0
        for qps in (25, 50, 100, 150, 200, 300):
            p = open_loop(url, qps, duration)
            points.append(p)
            if p["completion_ratio"] >= 0.9:
                best = max(best, p["completed_rows_per_s"])
            else:
                break
        return points, best

    results = {}
    for n in (1, 3):
        workers = spawn_local_workers(
            n, spec, extra_env={"JAX_PLATFORMS": "cpu"})
        router = FleetRouter(workers, poll_interval=0.25,
                             owns_workers=True).start(port=0)
        url = f"http://127.0.0.1:{router.port}"
        try:
            t_end = time.monotonic() + 15.0
            while time.monotonic() < t_end and \
                    not all(w.models for w in router.workers):
                time.sleep(0.05)
            open_loop(url, 50, 0.3)   # warm the connections
            points, sat = saturation_sweep(url)
            results[f"workers_{n}"] = {"points": points,
                                       "saturation_rows_per_s": sat}
            if n == 3:
                half = max(25, int(sat / rows_per_request / 2))
                baseline = open_loop(url, half, duration)
                router.start_rollout(
                    "m", {"kind": "linear", "scale": 2.0,
                          "delay_ms": 20.0, "example_shape": [8],
                          "ladder": [1]},
                    version=2, fraction=0.25, min_samples=10 ** 9)
                in_rollout = open_loop(url, half, duration)
                results["rollout_in_progress"] = {
                    "offered_qps": half,
                    "baseline_p99_ms": baseline["p99_ms"],
                    "rollout_p99_ms": in_rollout["p99_ms"],
                    "mirrors": router.rollout._mirrors,
                }
                # direct vs routed hop at light load (10 qps: no
                # queueing on either side, so the delta IS the
                # router's added hop)
                w = router.workers[0]
                direct = open_loop(w.url, 10, 0.8)
                before = telemetry.get_registry().snapshot()
                routed = open_loop(url, 10, 0.8)
                after = telemetry.get_registry().snapshot()

                # hop decomposition (ISSUE 16): the router's own
                # dl4j_fleet_hop_seconds deltas over the routed run —
                # the phases partition the measured hop exactly, so
                # their means must cover >=90% of the router-hop mean
                # (the acceptance read; the residual is responses that
                # carried no Server-Timing header)
                def _delta(key):
                    return after.get(key, 0.0) - before.get(key, 0.0)

                phase_ms, phase_sum_s = {}, 0.0
                for phase in ("queue", "execute", "worker_other",
                              "transit"):
                    psum = _delta(
                        f'dl4j_fleet_hop_seconds_sum{{phase="{phase}"}}')
                    pcount = _delta(
                        f'dl4j_fleet_hop_seconds_count{{phase="{phase}"}}')
                    phase_sum_s += psum
                    phase_ms[phase] = round(
                        psum / max(pcount, 1) * 1e3, 3)
                hop_sum_s = hop_count = 0.0
                for key, v in after.items():
                    if key.startswith("dl4j_fleet_request_seconds_sum{"):
                        hop_sum_s += v - before.get(key, 0.0)
                    elif key.startswith(
                            "dl4j_fleet_request_seconds_count{"):
                        hop_count += v - before.get(key, 0.0)
                hop_mean_ms = hop_sum_s / max(hop_count, 1) * 1e3
                results["hop_decomposition"] = {
                    "phase_mean_ms": phase_ms,
                    "hop_mean_ms": round(hop_mean_ms, 3),
                    "coverage": round(
                        phase_sum_s / max(hop_sum_s, 1e-12), 4),
                }

                # SLO-evaluation overhead (ISSUE 16): one sampler tick
                # + burn evaluation over this populated registry,
                # amortized per request at the worker's default
                # sampling interval and the measured 3-worker
                # saturation — must be <=1% of the request's own cost
                slo_mod.declare(slo_mod.Slo(
                    "bench_hop", kind="latency",
                    metric='dl4j_fleet_request_seconds{worker="w0"}',
                    threshold=0.05, objective=0.99))
                timeseries.sample_now()   # warm the ring
                evals = 50
                t0 = time.perf_counter()
                for _ in range(evals):
                    timeseries.sample_now()
                eval_ms = (time.perf_counter() - t0) / evals * 1e3
                slo_mod.remove("bench_hop")
                interval = timeseries.DEFAULT_INTERVAL
                per_req_ms = eval_ms / max(interval * sat, 1e-9)
                results["slo_eval_overhead"] = {
                    "sample_plus_evaluate_ms": round(eval_ms, 4),
                    "interval_s": interval,
                    "amortized_per_request_ms_at_saturation": round(
                        per_req_ms, 6),
                    "pct_of_direct_p50": round(
                        per_req_ms / max(direct["p50_ms"], 1e-9) * 100,
                        4),
                }
                results["hop_overhead_ms"] = round(
                    routed["p50_ms"] - direct["p50_ms"], 2)
        finally:
            router.close()
    sat1 = results["workers_1"]["saturation_rows_per_s"]
    sat3 = results["workers_3"]["saturation_rows_per_s"]
    return {
        "metric": "fleet_router_3worker_saturation_rows_per_s",
        "value": sat3,
        "unit": "rows/s",
        "vs_baseline": None,
        "workers_1_saturation_rows_per_s": sat1,
        "scaling_x": round(sat3 / max(sat1, 1e-9), 2),
        "host_bound": _host_bound(),
        **results,
        "note": ("open-loop fixed-rate arrivals against subprocess "
                 "workers serving a 20ms serial host-side linear "
                 "model (batch-1 ladder: per-worker capacity exactly "
                 "50 rows/s), so the sweep measures the router's "
                 "scale-out and hop machinery, not model math; "
                 "rollout_in_progress compares client p99 at ~half "
                 "saturation with a 25% canary mirror active vs none; "
                 "hop_decomposition attributes the routed hop to "
                 "queue/execute/worker_other/transit via Server-Timing "
                 "subtraction (coverage = attributed/hop time), and "
                 "slo_eval_overhead amortizes one sample+evaluate tick "
                 "per request at the default 5s interval "
                 "(`python bench.py --only fleet`)"),
    }


def bench_fleet_loop(fill=40, n_baseline=80):
    """ISSUE 20: the closed loop, measured. Four numbers against a
    live 2-worker fleet on this box:

    - capture -> fine-tune -> publish -> promote wall clock
      (`loop_wall_s`): live traffic into the capture ring, a fresh
      model distilled from it at the `train` admission priority, the
      checkpoint pushed back through a `from_checkpoint` canary
      rollout, and the canary promoted fleet-wide;
    - serving p99 with vs without the concurrent fine-tune: the train
      class is capped and shed first (arbitration, not isolation —
      the fit still competes for the same cores, so the read is
      "bounded", not "free");
    - respawn MTTR: SIGKILL a spawned worker under traffic and time
      kill -> the respawned process routable again;
    - client-visible errors across the kill window (the router's
      retry budget + the respawner should hold this at 0).
    """
    import os
    import signal as _signal
    import tempfile
    import threading

    from deeplearning4j_tpu.fleet import (
        Autopilot, FleetFineTuner, Respawner, TrafficCapture)
    from deeplearning4j_tpu.fleet.router import (
        FleetRouter, TransportFailure, _http, spawn_local_workers)
    from deeplearning4j_tpu.serving.admission import AdmissionController
    from deeplearning4j_tpu.telemetry import flight

    def _tiny():
        from deeplearning4j_tpu.nn import (
            DenseLayer, InputType, MultiLayerNetwork,
            NeuralNetConfiguration, OutputLayer)
        from deeplearning4j_tpu.optimize.updaters import Adam

        conf = (NeuralNetConfiguration.Builder().seed(7)
                .updater(Adam(1e-2)).list()
                .layer(DenseLayer.Builder().nOut(8)
                       .activation("tanh").build())
                .layer(OutputLayer.Builder().nOut(2)
                       .activation("softmax").build())
                .setInputType(InputType.feedForward(3)).build())
        net = MultiLayerNetwork(conf)
        net.init()
        return net

    tmp = tempfile.mkdtemp(prefix="dl4j_fleet_loop_")
    mlp = {"name": "m", "version": 1, "kind": "mlp", "n_in": 3,
           "n_out": 2, "width": 8, "seed": 7, "example_shape": [3],
           "ladder": [1, 4]}
    spec = {"models": [mlp]}
    handles = spawn_local_workers(
        2, spec, base_dir=os.path.join(tmp, "fleet"), timeout=120.0,
        extra_env={"JAX_PLATFORMS": "cpu"})
    cap = TrafficCapture(sample_interval=1, max_records=512)
    router = FleetRouter(handles, poll_interval=0.1, capture=cap,
                         owns_workers=True,
                         retry_budget=4).start(port=0)
    url = f"http://127.0.0.1:{router.port}"
    rng = np.random.default_rng(5)
    stats = {"sent": 0, "ok": 0}

    def predict_once(lats=None):
        x = rng.normal(size=(2, 3)).astype(np.float32)
        t0 = time.perf_counter()
        try:
            status, _, _ = _http(
                f"{url}/serving/v1/models/m:predict",
                body=json.dumps({"instances": x.tolist()}).encode(),
                timeout=30.0)
        except TransportFailure:
            stats["sent"] += 1
            return 0
        stats["sent"] += 1
        stats["ok"] += status == 200
        if status == 200 and lats is not None:
            lats.append(time.perf_counter() - t0)
        return status

    def p99_ms(lats):
        return round(float(np.quantile(lats, 0.99)) * 1e3, 2) \
            if lats else 0.0

    results = {}
    try:
        # capture + unloaded baseline
        for _ in range(fill):
            predict_once()
        base_lat = []
        for _ in range(n_baseline):
            predict_once(base_lat)
        t_loop = time.perf_counter()
        path = cap.save(os.path.join(tmp, "traffic.jsonl"),
                        append=True)

        # fine-tune at train priority while serving continues
        adm = AdmissionController(default_budget=8)
        ft = FleetFineTuner(
            router, "m", path, _tiny, os.path.join(tmp, "ckpt"),
            admission=adm, epochs=2, batch_size=8,
            spec_extra={"example_shape": [3]},
            rollout_kw={"fraction": 1.0, "min_samples": 5,
                        "p99_ratio": 100.0, "push_timeout": 120.0},
            everyNIterations=1).start()
        during = []
        while ft._thread.is_alive():
            predict_once(during)
            time.sleep(0.002)
        ft.join(60.0)
        t_trained = time.perf_counter()

        # drive the published canary to its verdict
        ctl = router.rollout
        deadline = time.monotonic() + 120.0
        while ctl is not None and not ctl.terminal() and \
                time.monotonic() < deadline:
            predict_once()
            time.sleep(0.002)
        loop_wall = time.perf_counter() - t_loop
        results.update({
            "finetune_state": ft.state,
            "published_version": ft.published_version,
            "rollout_state": None if ctl is None else ctl.state,
            "finetune_s": round(t_trained - t_loop, 2),
            "serving_p99_ms_baseline": p99_ms(base_lat),
            "serving_p99_ms_during_finetune": p99_ms(during),
            "train_sheds": next(
                (e.get("train_sheds") for e in
                 flight.get_recorder().events("finetune_complete")),
                None),
        })

        # respawn MTTR: kill a worker under traffic, time the revival
        rs = Respawner(router, max_respawns=3, spawn_timeout=120.0)
        ap = Autopilot(router, respawner=rs, interval=0.05).start()
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            _, _, hb = _http(url + "/healthz", timeout=10.0)
            if json.loads(hb)["fleet"]["routable"] == 2:
                break
            time.sleep(0.05)
        victim = router.workers[0]
        sent0, ok0 = stats["sent"], stats["ok"]
        t_kill = time.perf_counter()
        os.kill(victim.proc.pid, _signal.SIGKILL)
        mttr = None
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            predict_once()
            if victim.up and any(
                    e["outcome"] == "ok" for e in
                    flight.get_recorder().events("worker_respawn")):
                mttr = time.perf_counter() - t_kill
                break
            time.sleep(0.01)
        ap.close()
        results.update({
            "respawn_mttr_s": None if mttr is None else round(mttr, 2),
            "kill_window_errors": (stats["sent"] - sent0)
            - (stats["ok"] - ok0),
        })
    finally:
        router.close()
    return {
        "metric": "fleet_loop_capture_to_promoted_s",
        "value": round(loop_wall, 2),
        "unit": "s",
        "vs_baseline": None,
        "host_bound": _host_bound(),
        **results,
        "note": ("2 spawned CPU workers behind the router; loop wall "
                 "covers capture save -> distillation fine-tune at "
                 "train priority (admission-capped, shed first) -> "
                 "from_checkpoint canary -> fleet-wide promote, with "
                 "client traffic flowing throughout; p99 pair is the "
                 "concurrent-training tax on serving (same cores — "
                 "bounded, not free); respawn MTTR is SIGKILL -> "
                 "autopilot-respawned worker routable, with the "
                 "client-visible error count over that window "
                 "(`python bench.py --only fleet_loop`)"),
    }


def bench_sharded_serving(prompt_len=128, max_new=32, n_requests=6):
    """ISSUE 19: GSPMD-sharded serving vs the single-device reference.
    Two arms on one 4-way model-parallel mesh: (a) predict hop — the
    same column-parallel MLP served sharded and replicated through the
    same session/ladder, recording p50/p99 per path and the sharded
    hop overhead (GSPMD dispatch + per-device arg placement); (b)
    decode — a mesh-sharded paged-KV transformer placed OVER BUDGET
    (the memledger budget is set so the whole pool exceeds one
    device's headroom but each page shard fits), recording tokens/s,
    tokens/s/chip and TTFT p50/p99, with the unsharded twin's typed
    rejection asserted in the same row. benchdiff direction: the
    headline value is sharded decode tokens/s/chip (higher is
    better); hop_overhead_ms is the cost knob to watch."""
    import jax

    from deeplearning4j_tpu.parallel.mesh import MeshConfig
    from deeplearning4j_tpu.serving import (
        BucketLadder, DecodeEngine, FnServable, InferenceSession,
        ShardedServable, ShardedTransformerDecodeModel,
        TransformerDecodeModel, column_parallel_mlp)
    from deeplearning4j_tpu.telemetry import memledger

    devices = jax.devices()
    tp = min(4, len(devices))
    if tp < 2:
        raise RuntimeError(
            "sharded_serving needs >= 2 devices; `python bench.py "
            "--only sharded_serving` forces 4 host devices on CPU")
    mesh = MeshConfig(data=1, model=tp, devices=devices[:tp]).build()

    # --- predict arm: sharded vs replicated through one session -----
    sizes = (256, 1024, 256)
    fn, ref_fn, params, specs = column_parallel_mlp(mesh, sizes, seed=3)
    sess = InferenceSession()
    sess.register("sh", ShardedServable(fn, params, (sizes[0],), mesh,
                                        param_specs=specs),
                  ladder=BucketLadder([4]), warmup=True)
    sess.register("rep", FnServable(lambda x: ref_fn(params, x),
                                    (sizes[0],), dtype=np.float32),
                  ladder=BucketLadder([4]), warmup=True)
    x = np.random.default_rng(0).standard_normal(
        (4, sizes[0])).astype(np.float32)

    def time_predict(name, n=60):
        sess.predict(name, x)   # steady state before the clock starts
        lat = []
        for _ in range(n):
            t0 = time.perf_counter()
            sess.predict(name, x)
            lat.append(time.perf_counter() - t0)
        lat = np.asarray(lat) * 1e3
        return {"p50_ms": round(float(np.percentile(lat, 50)), 3),
                "p99_ms": round(float(np.percentile(lat, 99)), 3)}

    predict = {"sharded": time_predict("sh"),
               "replicated": time_predict("rep")}
    predict["hop_overhead_ms"] = round(
        predict["sharded"]["p50_ms"] - predict["replicated"]["p50_ms"],
        3)
    # unregister releases the predict arms' ledger claims (close()
    # alone keeps registry entries live) before the budget demo below
    sess.registry.unregister("sh")
    sess.registry.unregister("rep")
    sess.close()

    # --- decode arm: page-sharded KV pool, placed over budget --------
    # n_pages oversizes the POOL only (the attention loop runs over
    # max_pages_per_slot, so decode cost is untouched): a 32MB pool
    # against a 20MB device budget makes the placement genuinely
    # over-budget while the ~1MB of params stays noise
    pool_kw = dict(max_slots=4, page=32,
                   max_pages_per_slot=(prompt_len + max_new + 63)
                   // 32 + 1, n_pages=1023)
    base = TransformerDecodeModel.init(
        vocab=256, hidden=64, n_layers=2, n_heads=2,
        max_len=prompt_len + max_new + 64, seed=5, **pool_kw)
    sharded = ShardedTransformerDecodeModel(base.params, 2, mesh,
                                            **pool_kw)
    pool_total = sum(sharded.pool_device_bytes().values())
    # whole pool > one device's budget, but each page shard fits
    budget = 20 * 1024 * 1024
    memledger.configure(budget_bytes=budget)
    try:
        try:
            DecodeEngine(base, name="bench-sh-ref")
            unsharded_fate = "admitted (BUG: should not fit)"
        except memledger.CapacityError as e:
            unsharded_fate = f"rejected at {e.site}"
        engine = DecodeEngine(sharded, name="bench-sh").warmup()
        rng = np.random.default_rng(0)
        prompts = [list(rng.integers(0, 256, size=prompt_len + i))
                   for i in range(n_requests)]
        ttfts = []
        t0 = time.perf_counter()
        n_tokens = 0
        for prompt in prompts:
            req = engine.submit(prompt, max_new)
            t_sub = time.perf_counter()
            stream = req.tokens(timeout=600.0)
            next(stream)
            ttfts.append(time.perf_counter() - t_sub)
            n_tokens += 1 + sum(1 for _ in stream)
        wall = time.perf_counter() - t0
        engine.close()
    finally:
        memledger.configure(budget_bytes=None)
    lat = np.asarray(ttfts) * 1e3
    tokens_per_s = n_tokens / wall
    decode = {
        "tokens_per_s": round(tokens_per_s, 1),
        "tokens_per_s_per_chip": round(tokens_per_s / tp, 1),
        "ttft_p50_ms": round(float(np.percentile(lat, 50)), 2),
        "ttft_p99_ms": round(float(np.percentile(lat, 99)), 2),
        "pool_bytes": pool_total,
        "device_budget_bytes": budget,
        "pool_shards": sharded.pool_shards,
        "unsharded_twin": unsharded_fate,
    }
    return {
        "metric": "sharded_decode_tokens_per_s_per_chip",
        "value": decode["tokens_per_s_per_chip"],
        "unit": "tokens/s/chip",
        "vs_baseline": None,
        "host_bound": _host_bound(),
        "mesh": {"model": tp},
        "predict": predict,
        "decode": decode,
        "prompt_len": prompt_len,
        "max_new": max_new,
        "note": ("4-way model-parallel mesh; predict compares the same "
                 "column-parallel MLP served sharded vs replicated "
                 "(hop_overhead_ms = GSPMD dispatch + per-device arg "
                 "placement at p50); decode streams from a page-"
                 "sharded KV pool deliberately placed over a budget "
                 "one device cannot hold (the unsharded twin's typed "
                 "rejection is recorded in the row). CAVEAT: CPU row "
                 "is host-bound — virtual host devices share the same "
                 "silicon, so tokens/s/chip understates a real slice; "
                 "re-record on chip "
                 "(`python bench.py --only sharded_serving`)"),
    }


ALL_BENCHES = [("bert", bench_bert), ("lenet", bench_lenet),
               ("resnet50", bench_resnet50),
               ("resnet50_etl", bench_resnet_etl),
               ("etl", bench_etl),
               ("graves_lstm", bench_graves_lstm),
               ("word2vec", bench_word2vec),
               ("serving_latency", bench_serving_latency),
               ("serving_load", bench_serving_load),
               ("decode", bench_decode),
               ("health_overhead", bench_health_overhead),
               ("precision", bench_precision),
               ("resilience", bench_resilience),
               ("trace_overhead", bench_trace_overhead),
               ("profile", bench_profile),
               ("compile_ledger", bench_compile_ledger),
               ("memory", bench_memory),
               ("coldstart", bench_coldstart),
               ("fleet", bench_fleet),
               ("fleet_loop", bench_fleet_loop),
               ("sharded_serving", bench_sharded_serving)]


def _merge_bench_all(results, path="BENCH_ALL.json"):
    """Merge measured rows into BENCH_ALL.json instead of clobbering it.
    README calls this file the authoritative record of TPU-chip numbers
    (VERDICT r5 item 2: headline claims must exist as recorded rows), so
    rows measured on another backend land under a platform-suffixed key
    ('word2vec_cpu') and never displace a chip row. Every new row is
    stamped with its platform."""
    import jax

    backend = jax.default_backend()
    try:
        with open(path) as f:
            existing = json.load(f)
    except (OSError, ValueError):
        existing = {}
    for name, rec in results.items():
        rec = dict(rec)
        rec.setdefault("platform", backend)
        key = name if backend == "tpu" else f"{name}_{backend}"
        if "error" in rec and "error" not in existing.get(key, {"error": 1}):
            # a transient bench failure must not destroy a previously
            # measured row; record the failure beside it instead
            existing[key + "_error"] = rec
            continue
        existing[key] = rec
    with open(path, "w") as f:
        json.dump(existing, f, indent=1)
    return existing


def _flag_value(argv, flag, default=None, cast=str):
    if flag in argv:
        i = argv.index(flag) + 1
        if i >= len(argv):
            raise SystemExit(f"{flag} needs a value")
        return cast(argv[i])
    return default


def main():
    argv = sys.argv[1:]
    only = _flag_value(argv, "--only", "")
    if ("serving_load" in only or "sharded_serving" in only
            or "--all" in argv):
        # the replica and sharded benches want a multi-device CPU mesh;
        # the flag only affects the host platform (harmless on TPU) and
        # must be set BEFORE the first jax import
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
    words = _flag_value(argv, "--words", 10_000_000, int)
    benches = dict(ALL_BENCHES)
    benches["word2vec"] = lambda: bench_word2vec(words)
    if "--only" in argv:
        # subset run that MERGES into BENCH_ALL.json, e.g.
        #   python bench.py --only word2vec,serving_latency [--words N]
        names = _flag_value(argv, "--only").split(",")
        unknown = [n for n in names if n not in benches]
        if unknown:
            raise SystemExit(f"unknown bench {unknown}; "
                             f"choose from {sorted(benches)}")
        results = {}
        for name in names:
            try:
                results[name] = benches[name]()
            except Exception as e:
                results[name] = {"error": f"{type(e).__name__}: {e}"}
            print(json.dumps({name: results[name]}))
        _merge_bench_all(results)
        return
    if "--all" in argv:
        results = {}
        for name, _ in ALL_BENCHES:
            fn = benches[name]
            try:
                results[name] = fn()
            except Exception as e:  # record, keep measuring the rest
                results[name] = {"error": f"{type(e).__name__}: {e}"}
            print(json.dumps({name: results[name]}))
        _merge_bench_all(results)
        # driver line last: the flagship result, exactly the 4 contract
        # keys (and a valid record even if the bert bench errored)
        bert = results["bert"]
        if "metric" in bert:
            line = {k: bert[k] for k in
                    ("metric", "value", "unit", "vs_baseline")}
        else:
            line = {"metric": "bert_base_mlm_tokens_per_sec_per_chip",
                    "value": 0.0, "unit": "tokens/sec",
                    "vs_baseline": 0.0}
        print(json.dumps(line))
    else:
        out = bench_bert()
        print(json.dumps({k: out[k] for k in
                          ("metric", "value", "unit", "vs_baseline")}))


if __name__ == "__main__":
    main()
