"""Benchmark: flagship BERT-base MLM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline note (BASELINE.md): the reference publishes no in-tree numbers
(`published: {}`), so vs_baseline is reported against BASELINE.json's
north-star target of 40% MFU — vs_baseline = measured_MFU / 0.40; >1.0
beats the target. Peak bf16 throughput per TPU v5e chip: 197 TFLOP/s.
"""

from __future__ import annotations

import json
import time

import numpy as np

V5E_PEAK_BF16 = 197e12
MFU_TARGET = 0.40


def train_flops_per_step(cfg, batch, seq):
    """fwd+bwd ~= 3x fwd. Per token, each layer's matmuls cost
    2*h*3h (QKV) + 2*h*h (attn out) + 2*2*h*f (FFN pair); attention
    adds 2*2*T*h per token (QK^T and PV); the tied LM head adds 2*h*V."""
    h, f, L, v = cfg.hidden, cfg.ffn, cfg.num_layers, cfg.vocab_size
    tokens = batch * seq
    fwd = tokens * L * (2 * h * 3 * h + 2 * h * h + 4 * h * f)
    fwd += tokens * L * (4 * seq * h)
    fwd += tokens * 2 * h * v
    return 3 * fwd


def main():
    import jax

    from deeplearning4j_tpu.models.bert import (
        BertConfig, BertTrainer, synthetic_mlm_batch)
    from deeplearning4j_tpu.parallel.mesh import MeshConfig

    cfg = BertConfig(vocab_size=30522, hidden=768, num_layers=12,
                     num_heads=12, ffn=3072, max_len=512)
    batch, seq = 16, 512
    mesh = MeshConfig(data=1, devices=jax.devices()[:1]).build()
    trainer = BertTrainer(cfg, mesh, lr=1e-4)
    tokens, labels = synthetic_mlm_batch(cfg, batch, seq, seed=0)

    # warmup/compile; float() forces a device->host read because
    # block_until_ready does not synchronize on the experimental axon
    # platform
    float(trainer.train_step(tokens, labels))
    float(trainer.train_step(tokens, labels))

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        loss = trainer.train_step(tokens, labels)
    float(loss)  # sync
    dt = (time.perf_counter() - t0) / n_steps

    tokens_per_sec = batch * seq / dt
    mfu = train_flops_per_step(cfg, batch, seq) / dt / V5E_PEAK_BF16
    print(json.dumps({
        "metric": "bert_base_mlm_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / MFU_TARGET, 3),
    }))


if __name__ == "__main__":
    main()
