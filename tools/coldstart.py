#!/usr/bin/env python
"""coldstart: cold vs warm process-start report for the persistent
executable store (ISSUE 13).

Every trial is a REAL process restart (subprocess), not an in-process
re-register — in-process numbers flatter the warm path because jit
tracing caches, weight-init executables, and the jax runtime are
already live. Two sites are measured:

- **serving**: register a 3-bucket servable ladder with warmup; the
  timed window is the `register(..., warmup=True)` call;
- **resume**: a Supervisor kill-and-resume — one child trains under a
  Supervisor and exits (the "kill"), the next child builds the same
  Supervisor over the same checkpoint dir and runs to the total epoch
  budget; the timed window is `sup.run(...)`.

Each site runs cold (empty store) then warm (the store the cold run
populated). Zero-XLA-compile warm starts are asserted through the
compile ledger (causes all `cache_hit`) and the `dl4j_compile_total`
delta — not timing.

Usage::

    python tools/coldstart.py                 # tmp store, full report
    python tools/coldstart.py --store DIR     # inspect/extend a store
    python tools/coldstart.py --json          # machine-readable report

``bench.py --only coldstart`` runs the same trials and records the
``coldstart`` row into BENCH_ALL.json.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

# compile-heavy enough that the XLA ladder dominates a cold start (a
# production model compiles for seconds; this one for hundreds of ms),
# small enough for CI: 8x384 MLP, 3 serving buckets, short supervised
# fit
WIDTH, DEPTH, NIN, NOUT = 384, 8, 64, 8
BUCKETS = (1, 8, 32)
TRAIN_STEPS_PER_EPOCH, TRAIN_EPOCHS = 4, 2


def _build_net(seed=7):
    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)
    from deeplearning4j_tpu.optimize.updaters import Adam

    b = (NeuralNetConfiguration.Builder().seed(seed).updater(Adam(1e-3))
         .list())
    b = b.layer(DenseLayer.Builder().nIn(NIN).nOut(WIDTH)
                .activation("tanh").build())
    for _ in range(DEPTH - 2):
        b = b.layer(DenseLayer.Builder().nOut(WIDTH)
                    .activation("tanh").build())
    b = b.layer(OutputLayer.Builder().nOut(NOUT).activation("softmax")
                .lossFunction(LossFunction.MCXENT).build())
    return MultiLayerNetwork(b.build()).init()


def _train_data():
    import numpy as np

    rng = np.random.default_rng(0)
    X = rng.normal(size=(TRAIN_STEPS_PER_EPOCH * 16, NIN)).astype(
        np.float32)
    y = np.eye(NOUT, dtype=np.float32)[
        rng.integers(0, NOUT, len(X))]
    return [(X[i:i + 16], y[i:i + 16])
            for i in range(0, len(X), 16)]


def _compile_total():
    from deeplearning4j_tpu import telemetry

    try:
        return float(telemetry.get_registry()
                     .counter("dl4j_compile_total").value)
    except Exception:
        return 0.0


def _store_modes():
    """{mode: total_seconds} from the dl4j_compile_seconds histogram."""
    from deeplearning4j_tpu import telemetry

    out = {}
    try:
        fam = telemetry.get_registry().histogram(
            "dl4j_compile_seconds", labelnames=("mode",))
        for key, hist in fam.children():
            mode = dict(key).get("mode", "?")
            out[mode] = round(out.get(mode, 0.0) + hist.sum, 6)
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# child trials (each runs in its own interpreter)
# ---------------------------------------------------------------------------

def child_serving():
    from deeplearning4j_tpu import compilestore, telemetry
    from deeplearning4j_tpu.serving import BucketLadder, InferenceSession
    from deeplearning4j_tpu.telemetry import compile_ledger

    telemetry.enable()
    # session first: its store touch starts the code-epoch sweep in
    # the background while the net builds
    session = InferenceSession()
    net = _build_net()
    c0 = _compile_total()
    t0 = time.perf_counter()
    session.register("coldstart", net, example_shape=(NIN,),
                     ladder=BucketLadder(BUCKETS), warmup=True)
    seconds = time.perf_counter() - t0
    causes = compile_ledger.get_ledger().causes("coldstart:v1")
    out = {
        "register_seconds": round(seconds, 4),
        "compiles": _compile_total() - c0,
        "causes": causes,
        "modes": _store_modes(),
        "store": compilestore.describe(),
    }
    session.close()
    return out


def _supervisor(ckpt_dir):
    from deeplearning4j_tpu.resilience import Supervisor, SupervisorConfig

    return Supervisor(_build_net, ckpt_dir,
                      config=SupervisorConfig(max_restarts=1),
                      everyNIterations=2)


def child_train(ckpt_dir):
    """The pre-kill half: supervised fit for ONE epoch of the total
    budget, then exit — the process death IS the kill."""
    from deeplearning4j_tpu import telemetry

    telemetry.enable()
    sup = _supervisor(ckpt_dir)
    t0 = time.perf_counter()
    sup.run(_train_data(), epochs=1)
    return {"train_seconds": round(time.perf_counter() - t0, 4)}


def child_resume(ckpt_dir):
    """The post-kill half: the same Supervisor over the same checkpoint
    dir runs the REMAINING budget; the ledger says whether its train
    step compiled or deserialized."""
    from deeplearning4j_tpu import compilestore, telemetry
    from deeplearning4j_tpu.telemetry import compile_ledger

    telemetry.enable()
    sup = _supervisor(ckpt_dir)
    c0 = _compile_total()
    t0 = time.perf_counter()
    net = sup.run(_train_data(), epochs=TRAIN_EPOCHS)
    seconds = time.perf_counter() - t0
    import numpy as np

    return {
        "resume_seconds": round(seconds, 4),
        "compiles": _compile_total() - c0,
        "fit_causes": compile_ledger.get_ledger().causes("fit"),
        "modes": _store_modes(),
        "iteration": net._iteration,
        "params_sha": __import__("hashlib").sha256(
            np.ascontiguousarray(
                net.params().toNumpy()).tobytes()).hexdigest()[:16],
        "store": compilestore.describe(),
    }


CHILDREN = {"serving": child_serving, "train": child_train,
            "resume": child_resume}


def run_child(kind, store_dir, ckpt_dir=None, timeout=600):
    """Spawn one trial in a fresh interpreter; returns its JSON row."""
    env = dict(os.environ)
    env["DL4J_EXECUTABLE_STORE"] = store_dir
    # hard-pin children to the host platform: the bench row is stamped
    # platform="cpu"/host_bound, and a parent holding the chip cannot
    # hand it to subprocesses anyway — inheriting a JAX_PLATFORMS=tpu
    # would crash the trials or mislabel chip numbers as cpu
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__), "--child", kind]
    if ckpt_dir:
        cmd += ["--ckpt", ckpt_dir]
    proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                          cwd=ROOT, timeout=timeout)
    if proc.returncode != 0:
        raise RuntimeError(
            f"coldstart child {kind} failed:\n{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def run_report(store_dir=None, ckpt_dir=None):
    """The full cold/warm matrix. Returns the report dict."""
    tmp = tempfile.TemporaryDirectory(prefix="dl4j-coldstart-")
    try:
        if store_dir is None:
            store_dir = os.path.join(tmp.name, "store")
        if ckpt_dir is None:
            ckpt_dir = os.path.join(tmp.name, "ckpt")
        serving_cold = run_child("serving", store_dir)
        serving_warm = run_child("serving", store_dir)
        run_child("train", store_dir, ckpt_dir)
        # each resume gets its OWN copy of the post-kill checkpoint: a
        # resume RUNS the remaining epoch budget and checkpoints, so
        # sharing the dir would leave the second trial nothing to do.
        # Copies live under the tmp root (cleaned up on exit; a
        # caller-supplied --ckpt dir is never written beside)
        import shutil

        warm_ckpt = os.path.join(tmp.name, "ckpt-warm")
        cold_ckpt = os.path.join(tmp.name, "ckpt-cold")
        shutil.copytree(ckpt_dir, warm_ckpt)
        shutil.copytree(ckpt_dir, cold_ckpt)
        # warm resume: store was populated by the train child
        resume_warm = run_child("resume", store_dir, warm_ckpt)
        # cold resume: same checkpoint, EMPTY store (a sibling dir —
        # never inside the warm root, its entries must not pollute the
        # report's store listing) — what a restart cost before ISSUE 13
        cold_store = os.path.join(tmp.name, "cold-store")
        resume_cold = run_child("resume", cold_store, cold_ckpt)
        from deeplearning4j_tpu.compilestore import ExecutableStore

        report = {
            "serving": {"cold": serving_cold, "warm": serving_warm,
                        "speedup": round(
                            serving_cold["register_seconds"]
                            / max(serving_warm["register_seconds"],
                                  1e-9), 2)},
            "resume": {"cold": resume_cold, "warm": resume_warm,
                       "speedup": round(
                           resume_cold["resume_seconds"]
                           / max(resume_warm["resume_seconds"],
                                 1e-9), 2)},
            "store_contents": ExecutableStore(store_dir).contents(),
        }
        return report
    finally:
        if tmp is not None:
            tmp.cleanup()


def _print_report(report):
    s = report["serving"]
    r = report["resume"]
    print("== serving: 3-bucket registration (fresh process each) ==")
    print(f"  cold: {s['cold']['register_seconds']:.3f}s "
          f"({s['cold']['compiles']:.0f} XLA compiles, "
          f"causes {s['cold']['causes']})")
    print(f"  warm: {s['warm']['register_seconds']:.3f}s "
          f"({s['warm']['compiles']:.0f} XLA compiles, "
          f"causes {s['warm']['causes']})")
    print(f"  speedup: {s['speedup']}x")
    print("== supervisor kill-and-resume ==")
    print(f"  cold store: {r['cold']['resume_seconds']:.3f}s "
          f"({r['cold']['compiles']:.0f} XLA compiles, "
          f"fit causes {r['cold']['fit_causes']})")
    print(f"  warm store: {r['warm']['resume_seconds']:.3f}s "
          f"({r['warm']['compiles']:.0f} XLA compiles, "
          f"fit causes {r['warm']['fit_causes']})")
    print(f"  speedup: {r['speedup']}x  params_sha "
          f"{r['warm']['params_sha']} "
          f"(== cold: {r['warm']['params_sha'] == r['cold']['params_sha']})")
    print("== store contents ==")
    for row in report["store_contents"]:
        print(f"  {row['key'][:16]}...  {row['bytes']:>8} B  "
              f"site={row.get('site')}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", choices=sorted(CHILDREN),
                    help="internal: run one trial in this process")
    ap.add_argument("--store", help="store dir (default: fresh tmp)")
    ap.add_argument("--ckpt", help="checkpoint dir (resume trials)")
    ap.add_argument("--json", action="store_true",
                    help="print the report as JSON")
    args = ap.parse_args(argv)
    if args.child:
        fn = CHILDREN[args.child]
        out = fn(args.ckpt) if args.child in ("train", "resume") \
            else fn()
        print(json.dumps(out))
        return 0
    report = run_report(store_dir=args.store, ckpt_dir=args.ckpt)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        _print_report(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
