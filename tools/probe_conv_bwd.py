"""Isolate the conv backward lowering at early-ResNet shapes (lean).

probe_block_train r4: s0/s1 block backward runs at 15-23% of peak while
the forward hits 32-62%. Times dx (transposed conv) and dW (correlation)
separately per shape, vs a dot-based dW reformulation
(conv_general_dilated_patches + one huge-K dot_general).
Fixed two-point chains (k and 5k) slope out the tunnel RTT.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V5E_PEAK_BF16 = 197e12


def slope(step_fn, x0, k1, reps=3):
    def chain_t(iters):
        @jax.jit
        def chain(a):
            def body(carry, _):
                return step_fn(carry), None
            c, _ = lax.scan(body, a, None, length=iters)
            return jnp.sum(c[..., :1].astype(jnp.float32))

        float(chain(x0))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(x0))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chain_t(k1)
    t2 = chain_t(5 * k1)
    return (t2 - t1) / (4 * k1)


def conv(x, w):
    return lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bench_shape(n, h, cin, cout, kh, k1):
    flops = 2 * n * h * h * kh * kh * cin * cout
    x = (jax.random.normal(jax.random.key(0), (n, h, h, cin), jnp.float32)
         * 0.1).astype(jnp.bfloat16)
    w = (jax.random.normal(jax.random.key(1), (kh, kh, cin, cout),
                           jnp.float32) * 0.05).astype(jnp.bfloat16)
    dy = (jax.random.normal(jax.random.key(2), (n, h, h, cout),
                            jnp.float32) * 0.1).astype(jnp.bfloat16)
    out = {"n": n, "h": h, "cin": cin, "cout": cout, "k": kh}

    def dx_step(xx):
        _, vjp = jax.vjp(lambda a: conv(a, w), xx)
        (gx,) = vjp(dy + xx[..., :1] * jnp.bfloat16(1e-30))
        return gx * jnp.bfloat16(0.999) if cin == cout else \
            gx * jnp.bfloat16(0.999)
    per = slope(dx_step, x, k1)
    out["dx_ms"] = round(per * 1e3, 3)
    out["dx_eff"] = round(flops / per / V5E_PEAK_BF16, 3)

    def dw_step(xx):
        gw = jax.grad(lambda ww: jnp.sum(
            conv(xx, ww).astype(jnp.float32) * dy.astype(jnp.float32)))(w)
        return xx + (jnp.sum(gw) * 1e-30).astype(jnp.bfloat16)
    per = slope(dw_step, x, k1)
    out["dw_ms"] = round(per * 1e3, 3)
    out["dw_eff"] = round(flops / per / V5E_PEAK_BF16, 3)

    if kh == 3:
        def dw_dot_step(xx):
            p = lax.conv_general_dilated_patches(
                xx, (3, 3), (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            gw = lax.dot_general(
                p.reshape(-1, cin * 9), dy.reshape(-1, cout),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return xx + (jnp.sum(gw) * 1e-30).astype(jnp.bfloat16)
        per = slope(dw_dot_step, x, k1)
        out["dw_dot_ms"] = round(per * 1e3, 3)
        out["dw_dot_eff"] = round(flops / per / V5E_PEAK_BF16, 3)

    print(json.dumps(out), flush=True)


bench_shape(256, 56, 64, 64, 3, 60)     # s0 conv2
bench_shape(256, 56, 256, 64, 1, 60)    # s0 conv1
bench_shape(256, 28, 128, 128, 3, 60)   # s1 conv2
