"""Component timing for the fused bottleneck kernel: where do the
non-MXU microseconds go? Variants (s2 shape, g=4, b256):
  matmuls   - dots only, no epilogues/masks/pad (UNSOUND numerics, timing only)
  +pad      - dots + padded-scratch staging for conv2
  +mask     - + the 9 edge masks
  +epi_f32  - + f32 affine/relu epilogues (the v1 kernel = probe_fused_block 2d)
  folded    - scales folded into weight columns outside; bf16 epilogues;
              masks folded into a single bf16 multiply on y1... (sound)
"""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

V5E_PEAK_BF16 = 197e12
H, C, F = 14, 1024, 256
N, G, K = 256, 4, 40
M = G * H * H
FLOPS = N * 2 * H * H * (C * F + 9 * F * F + F * C)

dot = functools.partial(
    jax.lax.dot_general, dimension_numbers=(((1,), (0,)), ((), ())),
    preferred_element_type=jnp.float32)


def k_matmuls(x_ref, w1_ref, w2_ref, w3_ref, o_ref):
    y1 = dot(x_ref[...], w1_ref[...]).astype(jnp.bfloat16)
    acc = jnp.zeros((M, F), jnp.float32)
    for i in range(9):
        acc += dot(y1, w2_ref[i])
    y2 = acc.astype(jnp.bfloat16)
    o_ref[...] = dot(y2, w3_ref[...]).astype(jnp.bfloat16)


def k_pad(x_ref, w1_ref, w2_ref, w3_ref, o_ref, pad_ref):
    pad = H + 1
    y1 = dot(x_ref[...], w1_ref[...]).astype(jnp.bfloat16)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[pad:pad + M, :] = y1
    acc = jnp.zeros((M, F), jnp.float32)
    for ky in range(3):
        for kx in range(3):
            off = (ky - 1) * H + (kx - 1)
            acc += dot(pad_ref[pad + off:pad + off + M, :],
                       w2_ref[ky * 3 + kx])
    y2 = acc.astype(jnp.bfloat16)
    o_ref[...] = dot(y2, w3_ref[...]).astype(jnp.bfloat16)


def k_mask(x_ref, w1_ref, w2_ref, w3_ref, o_ref, pad_ref):
    pad = H + 1
    y1 = dot(x_ref[...], w1_ref[...]).astype(jnp.bfloat16)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[pad:pad + M, :] = y1
    rows = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0)
    yy = (rows % (H * H)) // H
    xx = rows % H
    acc = jnp.zeros((M, F), jnp.float32)
    for ky in range(3):
        for kx in range(3):
            off = (ky - 1) * H + (kx - 1)
            ok = ((yy + (ky - 1) >= 0) & (yy + (ky - 1) < H) &
                  (xx + (kx - 1) >= 0) & (xx + (kx - 1) < H))
            acc += dot(pad_ref[pad + off:pad + off + M, :],
                       w2_ref[ky * 3 + kx]) * ok.astype(jnp.float32)
    y2 = acc.astype(jnp.bfloat16)
    o_ref[...] = dot(y2, w3_ref[...]).astype(jnp.bfloat16)


def k_folded(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref,
             o_ref, pad_ref):
    """Sound kernel: scales pre-folded into weight columns; biases bf16;
    epilogues in bf16; edge handling via zeroing the pad borders only
    (no 9 masks): contributions from out-of-image x-positions come from
    the zeroed pad rows... NOTE x-edge wrap reads a real neighbor row,
    so x-masks stay but as a single bf16 y1-side trick: we instead mask
    the SLICE rows via two precomputed bf16 row masks applied to the
    dot RESULT only for the 6 kx!=1 taps."""
    pad = H + 1
    y1 = dot(x_ref[...], w1_ref[...]).astype(jnp.bfloat16)
    y1 = jnp.maximum(y1 + b1_ref[...].astype(jnp.bfloat16), 0)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[pad:pad + M, :] = y1
    rows = jax.lax.broadcasted_iota(jnp.int32, (M, 1), 0)
    xx = rows % H
    left_ok = (xx > 0).astype(jnp.bfloat16)     # can read x-1
    right_ok = (xx < H - 1).astype(jnp.bfloat16)
    acc = jnp.zeros((M, F), jnp.float32)
    for ky in range(3):
        for kx in range(3):
            off = (ky - 1) * H + (kx - 1)
            sl = pad_ref[pad + off:pad + off + M, :]
            if kx == 0:
                sl = sl * left_ok
            elif kx == 2:
                sl = sl * right_ok
            acc += dot(sl, w2_ref[ky * 3 + kx])
    y2 = jnp.maximum(acc.astype(jnp.bfloat16) +
                     b2_ref[...].astype(jnp.bfloat16), 0)
    y3 = dot(y2, w3_ref[...]).astype(jnp.bfloat16)
    o_ref[...] = jnp.maximum(
        y3 + b3_ref[...].astype(jnp.bfloat16) + x_ref[...], 0)


CP = pltpu.CompilerParams(vmem_limit_bytes=100 * 1024 * 1024)
wspec = lambda shp: pl.BlockSpec(shp, lambda i: (0,) * len(shp))
xspec = pl.BlockSpec((M, C), lambda i: (i, 0))


def build(kern, extra_w=(), scratch=False):
    specs = [xspec, wspec((C, F))]
    for shp in extra_w:
        specs.append(wspec(shp))

    def run(x, *ws):
        return pl.pallas_call(
            kern, grid=(N // G,), in_specs=specs,
            out_specs=xspec,
            out_shape=jax.ShapeDtypeStruct((N * H * H, C), jnp.bfloat16),
            scratch_shapes=([pltpu.VMEM((M + 2 * (H + 1), F),
                                        jnp.bfloat16)] if scratch else []),
            compiler_params=CP,
        )(x, *ws)
    return run


def bench(fn, args, label):
    @jax.jit
    def chain(x, *ws):
        def body(y, _):
            return fn(y, *ws), 0.0
        y, _ = lax.scan(body, x, None, length=K)
        return y

    y = chain(*args)
    float(jnp.sum(y.astype(jnp.float32)))
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        y = chain(*args)
        float(jnp.sum(y.astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / K)
    print(json.dumps({"variant": label, "ms": round(best * 1e3, 3),
                      "frac_of_peak": round(FLOPS / best / V5E_PEAK_BF16,
                                            4)}), flush=True)


rng = np.random.default_rng(0)
x = jnp.asarray(rng.normal(size=(N * H * H, C)) * 0.3, jnp.bfloat16)
w1 = jnp.asarray(rng.normal(size=(C, F)) * 0.04, jnp.bfloat16)
w2 = jnp.asarray(rng.normal(size=(9, F, F)) * 0.02, jnp.bfloat16)
w3 = jnp.asarray(rng.normal(size=(F, C)) * 0.06, jnp.bfloat16)
b1 = jnp.zeros((1, F), jnp.float32)
b2 = jnp.zeros((1, F), jnp.float32)
b3 = jnp.zeros((1, C), jnp.float32)

bench(build(k_matmuls, [(9, F, F), (F, C)]), (x, w1, w2, w3), "matmuls")
bench(build(k_pad, [(9, F, F), (F, C)], scratch=True),
      (x, w1, w2, w3), "+pad")
bench(build(k_mask, [(9, F, F), (F, C)], scratch=True),
      (x, w1, w2, w3), "+mask")


def build2(kern):
    specs = [xspec, wspec((C, F)), wspec((1, F)), wspec((9, F, F)),
             wspec((1, F)), wspec((F, C)), wspec((1, C))]

    def run(x, *ws):
        return pl.pallas_call(
            kern, grid=(N // G,), in_specs=specs, out_specs=xspec,
            out_shape=jax.ShapeDtypeStruct((N * H * H, C), jnp.bfloat16),
            scratch_shapes=[pltpu.VMEM((M + 2 * (H + 1), F),
                                       jnp.bfloat16)],
            compiler_params=CP,
        )(x, *ws)
    return run


bench(build2(k_folded), (x, w1, b1, w2, b2, w3, b3), "folded")
