"""A/B probe for BERT step-time on the real chip: attention impl x
dropout x batch size. Prints one JSON line per variant."""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def probe(attention_impl, batch, dropout, k=10, trials=3):
    import jax

    from deeplearning4j_tpu.models.bert import (
        BertConfig, BertTrainer, synthetic_mlm_batch)
    from deeplearning4j_tpu.parallel.mesh import MeshConfig

    cfg = BertConfig(vocab_size=30522, hidden=768, num_layers=12,
                     num_heads=12, ffn=3072, max_len=512,
                     dropout=dropout, attention_impl=attention_impl)
    seq = 512
    mesh = MeshConfig(data=1, devices=jax.devices()[:1]).build()
    trainer = BertTrainer(cfg, mesh, lr=1e-4)
    stacks = [synthetic_mlm_batch(cfg, batch, seq, seed=s) for s in range(k)]
    tokens_k = np.stack([s[0] for s in stacks])
    labels_k = np.stack([s[1] for s in stacks])
    float(trainer.train_steps(tokens_k, labels_k)[-1])
    float(trainer.train_steps(tokens_k, labels_k)[-1])
    dt = float("inf")
    for _ in range(trials):
        t0 = time.perf_counter()
        losses = trainer.train_steps(tokens_k, labels_k)
        float(losses[-1])
        dt = min(dt, (time.perf_counter() - t0) / k)
    tps = batch * seq / dt
    print(json.dumps({"impl": attention_impl, "batch": batch,
                      "dropout": dropout, "ms_per_step": round(dt * 1e3, 2),
                      "tokens_per_sec": round(tps, 1)}), flush=True)
    del trainer


# extra variant list for round-2 tuning: python tools/probe_bert.py dpa
DPA_VARIANTS = [("dpa", 16, 0.1), ("dense", 24, 0.1), ("dpa", 24, 0.1)]

if __name__ == "__main__":
    import sys
    variants = [
        ("flash", 16, 0.1),
        ("dense", 16, 0.1),
        ("flash", 16, 0.0),
        ("dense", 16, 0.0),
        ("flash", 32, 0.1),
        ("dense", 32, 0.1),
        ("flash", 64, 0.1),
    ]
    if len(sys.argv) > 1 and sys.argv[1] == "dpa":
        variants = DPA_VARIANTS
    elif len(sys.argv) > 1:
        sel = int(sys.argv[1])
        variants = variants[sel:sel + 1]
    for v in variants:
        try:
            probe(*v)
        except Exception as e:
            print(json.dumps({"impl": v[0], "batch": v[1], "dropout": v[2],
                              "error": f"{type(e).__name__}: {e}"[:200]}),
                  flush=True)
