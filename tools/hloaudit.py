#!/usr/bin/env python
"""hloaudit: the per-model XLA fusion/remat audit CLI (ISSUE 11).

AOT-lowers and compiles a flagship model's train step (and optionally
its inference function), runs ``telemetry.hlo_audit`` over the
optimized HLO, registers the executable in the compile ledger (site
``hloaudit:<model>``), and prints the structural report ROADMAP item 4
asks for: fusion count, unfused dot/conv ops, collective ops, remat
markers, and the largest buffers. Committed findings live in
docs/HLO_AUDIT.md.

Usage::

    python tools/hloaudit.py --model resnet50 [--batch 8]
    python tools/hloaudit.py --model bert --batch 4 --seq 128
    python tools/hloaudit.py --models resnet50,bert,graves_lstm --json out.json

Models: mlp (smoke), resnet50, bert, graves_lstm. Nothing here touches
the serving/training hot paths — the lower+compile happens in this
process only (jax caches it, so re-running is cheap), and the audit is
a pure text parse.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _ones_like_batch(net, f, l):
    import numpy as np

    lmask = np.ones(l.shape[:1] + (() if l.ndim == 2 else (l.shape[2],)),
                    np.float32)
    return lmask


def audit_network(net, f, l, mode="train"):
    """Audit a MultiLayerNetwork's or ComputationGraph's compiled
    step/inference executable against one synthetic batch."""
    import jax
    import numpy as np

    from deeplearning4j_tpu.telemetry import hlo_audit

    is_graph = type(net).__name__ == "ComputationGraph"
    if mode == "infer":
        if is_graph:
            raise SystemExit("--mode infer supports sequential nets only")
        fn = net._infer_fn(False)
        args = (net._params, net._states, np.asarray(f))
    else:
        net._refresh_train_step()
        fn = net._train_step
        rng = jax.random.key(net.conf.seed + 1)
        if is_graph:
            inputs, labels, masks = net._feeds((f, l),
                                               with_ones_masks=True)
            args = (net._params, net._states, net._opt_states,
                    net._prec_state, inputs, labels, masks, rng, 0)
        else:
            lmask = _ones_like_batch(net, f, l)
            args = (net._params, net._states, net._opt_states,
                    net._prec_state, f, l, lmask, rng, 0)
    t0 = time.perf_counter()
    compiled = fn.lower(*args).compile()
    dt = time.perf_counter() - t0
    out = hlo_audit.audit_compiled(compiled)
    out["compile_seconds"] = round(dt, 3)
    return out, compiled, args


def build_mlp(batch):
    import numpy as np

    from deeplearning4j_tpu.nn import (
        DenseLayer, LossFunction, MultiLayerNetwork,
        NeuralNetConfiguration, OutputLayer)

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(128).nOut(256)
                   .activation("relu").build())
            .layer(OutputLayer.Builder().nOut(10).activation("softmax")
                   .lossFunction(LossFunction.MCXENT).build())
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    f = rng.normal(size=(batch, 128)).astype(np.float32)
    l = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    return net, f, l


def build_resnet50(batch):
    import numpy as np

    from deeplearning4j_tpu.models.zoo import ResNet50

    net = ResNet50(numClasses=1000, dataType="bfloat16").init()
    rng = np.random.default_rng(0)
    f = rng.normal(size=(batch, 3, 224, 224)).astype(np.float32)
    l = np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)]
    return net, f, l


def build_graves_lstm(batch, seq=50, vocab=77):
    import numpy as np

    from deeplearning4j_tpu.models.zoo import TextGenerationLSTM

    net = TextGenerationLSTM(vocabSize=vocab, hidden=256,
                             seqLength=seq).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (batch, seq + 1))
    f = np.eye(vocab, dtype=np.float32)[ids[:, :-1]].transpose(0, 2, 1)
    l = np.eye(vocab, dtype=np.float32)[ids[:, 1:]].transpose(0, 2, 1)
    return net, f, l


def audit_bert(batch, seq):
    """BERT-base MLM train step through BertTrainer's own jitted step
    (single-device mesh): the same executable bench.py's flagship row
    measures."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.models.bert import (
        BertConfig, BertTrainer, mlm_gather, synthetic_mlm_batch)
    from deeplearning4j_tpu.parallel.mesh import MeshConfig
    from deeplearning4j_tpu.telemetry import hlo_audit

    cfg = BertConfig(vocab_size=30522, hidden=768, num_layers=12,
                     num_heads=12, ffn=3072, max_len=512)
    mesh = MeshConfig(data=1, devices=jax.devices()[:1]).build()
    trainer = BertTrainer(cfg, mesh, lr=1e-4)
    tokens, labels = synthetic_mlm_batch(cfg, batch, seq, seed=0)
    positions, mlm_labels, weights = mlm_gather(
        labels, max_preds=trainer._max_preds(seq))
    rng = jax.random.key(1, impl="rbg")
    fn = trainer._build()
    args = (trainer.params, trainer.opt, jnp.asarray(tokens, jnp.int32),
            positions, mlm_labels, weights, rng,
            jnp.asarray(0, jnp.int32))
    t0 = time.perf_counter()
    compiled = fn.lower(*args).compile()
    dt = time.perf_counter() - t0
    out = hlo_audit.audit_compiled(compiled)
    out["compile_seconds"] = round(dt, 3)
    out["config"] = {"batch": batch, "seq": seq, "layers": cfg.num_layers,
                     "hidden": cfg.hidden}
    return out, compiled, args


def _ledger(model, compiled, args, seconds):
    """Register the audited executable in the process compile ledger so
    the CLI workflow and the live /debug/compiles view agree."""
    try:
        import jax

        from deeplearning4j_tpu.telemetry import compile_ledger

        leaves = jax.tree_util.tree_leaves(args)
        sig = tuple((tuple(getattr(x, "shape", ())),
                     str(getattr(x, "dtype", type(x).__name__)))
                    for x in leaves)
        compile_ledger.record_executable(
            f"hloaudit:{model}", compiled, sig, seconds=seconds,
            bucketed=False)
    except Exception as e:  # the report matters more than the ledger row
        print(f"[hloaudit] ledger registration failed: {e}",
              file=sys.stderr)


def run_model(model, batch, seq, mode):
    if model == "bert":
        out, compiled, args = audit_bert(batch or 4, seq or 128)
    else:
        builders = {"mlp": build_mlp, "resnet50": build_resnet50,
                    "graves_lstm": build_graves_lstm}
        if model not in builders:
            raise SystemExit(
                f"unknown model {model!r}; choose from "
                f"{sorted(builders) + ['bert']}")
        if model == "graves_lstm":
            net, f, l = build_graves_lstm(batch or 32, seq or 50)
        else:
            net, f, l = builders[model](batch or 8)
        out, compiled, args = audit_network(net, f, l, mode=mode)
        out["config"] = {"batch": int(f.shape[0]), "mode": mode}
    _ledger(model, compiled, args, out.get("compile_seconds"))
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", default=None)
    ap.add_argument("--models", default=None,
                    help="comma-separated list (one combined report)")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--seq", type=int, default=None)
    ap.add_argument("--mode", default="train",
                    choices=("train", "infer"))
    ap.add_argument("--json", default=None,
                    help="also write the report to this path")
    args = ap.parse_args(argv)
    names = ([m.strip() for m in args.models.split(",") if m.strip()]
             if args.models else [args.model or "mlp"])
    report = {}
    for name in names:
        print(f"[hloaudit] compiling + auditing {name} ...",
              file=sys.stderr)
        report[name] = run_model(name, args.batch, args.seq, args.mode)
    print(json.dumps(report, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
