"""Raw MXU efficiency vs matmul shape (Pallas grid kernel and XLA dot),
bf16 operands, f32 accumulate. Calibrates what fraction of the 197
TFLOPs peak each (M,K,N) sustains — the shape ceiling any conv
formulation inherits."""
from __future__ import annotations

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

V5E_PEAK_BF16 = 197e12
K_ITERS = 30


def bench_xla(m, k, n):
    a = jnp.asarray(np.random.default_rng(0).normal(size=(m, k)) * 0.1,
                    jnp.bfloat16)
    b = jnp.asarray(np.random.default_rng(1).normal(size=(k, n)) * 0.1,
                    jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        def body(c, _):
            # rotate the accumulator back into bf16 lhs-shaped input by
            # a cheap projection to keep a serial dependence
            y = lax.dot_general(c, b, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            return (c + y[:, :1].astype(jnp.bfloat16) * 1e-6), 0.0
        c, _ = lax.scan(body, a, None, length=K_ITERS)
        return c

    y = chain(a, b)
    float(jnp.sum(y.astype(jnp.float32)))
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        y = chain(a, b)
        float(jnp.sum(y.astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / K_ITERS)
    return 2 * m * k * n / best / V5E_PEAK_BF16


def bench_pallas(m, k, n, bm):
    """grid over M blocks of bm rows; weights resident."""
    def kern(a_ref, b_ref, o_ref):
        o_ref[...] = lax.dot_general(
            a_ref[...], b_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(jnp.bfloat16)

    def run(a, b):
        return pl.pallas_call(
            kern, grid=(m // bm,),
            in_specs=[pl.BlockSpec((bm, k), lambda i: (i, 0)),
                      pl.BlockSpec((k, n), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((m, n), jnp.bfloat16),
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=100 * 1024 * 1024),
        )(a, b)

    a = jnp.asarray(np.random.default_rng(0).normal(size=(m, k)) * 0.1,
                    jnp.bfloat16)
    b = jnp.asarray(np.random.default_rng(1).normal(size=(k, n)) * 0.1,
                    jnp.bfloat16)

    @jax.jit
    def chain(a, b):
        def body(c, _):
            y = run(c, b)
            return (c + y[:, :1] * jnp.bfloat16(1e-6)
                    if n == k else c + y[:, :1] * jnp.bfloat16(0)), 0.0
        c, _ = lax.scan(body, a, None, length=K_ITERS)
        return c

    y = chain(a, b)
    float(jnp.sum(y.astype(jnp.float32)))
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        y = chain(a, b)
        float(jnp.sum(y.astype(jnp.float32)))
        best = min(best, (time.perf_counter() - t0) / K_ITERS)
    return 2 * m * k * n / best / V5E_PEAK_BF16


shapes = [
    (784, 1024, 256), (1568, 1024, 256), (3136, 1024, 256),
    (784, 256, 256), (3136, 256, 256),
    (784, 256, 1024), (3136, 256, 1024),
    (4096, 4096, 4096), (8192, 2048, 2048),
    (50176, 1024, 256), (50176, 256, 256),
]
for m, k, n in shapes:
    e_xla = bench_xla(m, k, n)
    row = {"m": m, "k": k, "n": n, "xla": round(e_xla, 3)}
    for bm in (784, 3136):
        if m % bm == 0 and bm * (k + n) * 2 < 80e6:
            row[f"pallas_bm{bm}"] = round(bench_pallas(m, k, n, bm), 3)
    print(json.dumps(row), flush=True)
