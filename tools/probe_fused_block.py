"""Fused ResNet bottleneck block: Pallas vs XLA forward probe (round 4).

RESNET_MFU.md bounds XLA-lowered ResNet-50 at ~16% MFU and names a fused
custom backbone (conv+BN+relu chains in one kernel) as the untested
remaining lever; VERDICT r3 item 1 demands that hypothesis be proven or
broken. This probe measures ONE identity bottleneck block — the unit 12
of ResNet-50's 16 blocks reduce to — at stage shapes, comparing:

  xla:    conv1x1 -> affine -> relu -> conv3x3 -> affine -> relu
          -> conv1x1 -> affine -> +residual -> relu  (XLA-scheduled)
  pallas: the same math in ONE kernel, all intermediates VMEM-resident,
          per-image-group grid (halo = image border zero-pad, exact).

BN is folded to affine scale/shift in BOTH paths (isolates the fusion
question from batch-stats reduction strategy, which RESNET_MFU.md
bounds at ~1.4 MFU points).

Arithmetic intensity (s2 shape, b256): unfused, each conv round-trips
HBM for ~204 FLOP/byte < v5e ridge ~240 -> HBM-bound; fused reads X +
weights and writes OUT once: ~546 FLOP/byte -> compute-bound.

Run: python tools/probe_fused_block.py [--stage s2] [--g 8] [--k 20]
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

V5E_PEAK_BF16 = 197e12

# (H, C, F): spatial, block channels, bottleneck width
STAGES = {
    "s0": (56, 256, 64),
    "s1": (28, 512, 128),
    "s2": (14, 1024, 256),
    "s3": (7, 2048, 512),
}


def block_flops(h, c, f):
    return 2 * h * h * (c * f + 9 * f * f + f * c)


# ---------------------------------------------------------------------------
# Pallas fused forward
# ---------------------------------------------------------------------------

def _fused_kernel(h, g, x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref,
                  b2_ref, w3_ref, s3_ref, b3_ref, o_ref, pad_ref):
    dot = functools.partial(
        jax.lax.dot_general, dimension_numbers=(((3,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    x = x_ref[...]                                   # (g,h,h,C) bf16
    y1 = dot(x, w1_ref[...])                         # (g,h,h,F) f32
    y1 = y1 * s1_ref[...].reshape(1, 1, 1, -1) + \
        b1_ref[...].reshape(1, 1, 1, -1)
    y1 = jnp.maximum(y1, 0.0).astype(jnp.bfloat16)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[:, 1:h + 1, 1:h + 1, :] = y1
    acc = jnp.zeros(y1.shape, jnp.float32)
    for ky in range(3):
        for kx in range(3):
            acc += dot(pad_ref[:, ky:ky + h, kx:kx + h, :],
                       w2_ref[ky * 3 + kx])
    y2 = acc * s2_ref[...].reshape(1, 1, 1, -1) + \
        b2_ref[...].reshape(1, 1, 1, -1)
    y2 = jnp.maximum(y2, 0.0).astype(jnp.bfloat16)
    y3 = dot(y2, w3_ref[...])
    y3 = y3 * s3_ref[...].reshape(1, 1, 1, -1) + \
        b3_ref[...].reshape(1, 1, 1, -1)
    o_ref[...] = jnp.maximum(
        y3 + x.astype(jnp.float32), 0.0).astype(jnp.bfloat16)


def fused_block(x, params, g):
    """x: (N,H,H,C) bf16; params: w1 (C,F) w2 (9,F,F) w3 (F,C) bf16 +
    affine (1,F)/(1,C) f32 pairs; g images per grid cell."""
    n, h, _, c = x.shape
    f = params["w1"].shape[1]
    wspec = lambda shp: pl.BlockSpec(shp, lambda i: (0,) * len(shp))
    return pl.pallas_call(
        functools.partial(_fused_kernel, h, g),
        grid=(n // g,),
        in_specs=[
            pl.BlockSpec((g, h, h, c), lambda i: (i, 0, 0, 0)),
            wspec((c, f)), wspec((1, f)), wspec((1, f)),
            wspec((9, f, f)), wspec((1, f)), wspec((1, f)),
            wspec((f, c)), wspec((1, c)), wspec((1, c)),
        ],
        out_specs=pl.BlockSpec((g, h, h, c), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, h, c), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((g, h + 2, h + 2, f), jnp.bfloat16)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(x, params["w1"], params["s1"], params["b1"], params["w2"],
      params["s2"], params["b2"], params["w3"], params["s3"], params["b3"])



# ---------------------------------------------------------------------------
# Pallas fused forward, 2D formulation: all matmuls get M = g*h*h rows
# (the 4D variant leaves Mosaic looping tiny M=h dots). The 3x3 conv is
# 9 row-shifted masked 2D matmuls over one contiguous padded scratch:
# flat row index r = (img*h + y)*h + x, shift (dy,dx) = r + dy*h + dx;
# contributions whose (y+dy, x+dx) fall outside the image are zeroed by
# a mask computed from iota (exact: equals zero-padded SAME conv).
# ---------------------------------------------------------------------------

def _fused_kernel2d(h, g, x_ref, w1_ref, s1_ref, b1_ref, w2_ref, s2_ref,
                    b2_ref, w3_ref, s3_ref, b3_ref, o_ref, pad_ref):
    dot = functools.partial(
        jax.lax.dot_general, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m = g * h * h
    pad = h + 1                       # max |shift| = h + 1
    x = x_ref[...]                                   # (m, C) bf16
    y1 = dot(x, w1_ref[...])                         # (m, F) f32
    y1 = y1 * s1_ref[...] + b1_ref[...]
    y1 = jnp.maximum(y1, 0.0).astype(jnp.bfloat16)
    pad_ref[...] = jnp.zeros_like(pad_ref)
    pad_ref[pad:pad + m, :] = y1
    rows = jax.lax.broadcasted_iota(jnp.int32, (m, 1), 0)
    yy = (rows % (h * h)) // h
    xx = rows % h
    acc = jnp.zeros((m, y1.shape[1]), jnp.float32)
    for ky in range(3):
        for kx in range(3):
            off = (ky - 1) * h + (kx - 1)
            sl = pad_ref[pad + off:pad + off + m, :]
            ok = ((yy + (ky - 1) >= 0) & (yy + (ky - 1) < h) &
                  (xx + (kx - 1) >= 0) & (xx + (kx - 1) < h))
            acc += dot(sl, w2_ref[ky * 3 + kx]) * ok.astype(jnp.float32)
    y2 = acc * s2_ref[...] + b2_ref[...]
    y2 = jnp.maximum(y2, 0.0).astype(jnp.bfloat16)
    y3 = dot(y2, w3_ref[...])
    y3 = y3 * s3_ref[...] + b3_ref[...]
    o_ref[...] = jnp.maximum(
        y3 + x.astype(jnp.float32), 0.0).astype(jnp.bfloat16)


def fused_block2d(x, params, g):
    n, h, _, c = x.shape
    f = params["w1"].shape[1]
    m = g * h * h
    x2 = x.reshape(n * h * h, c)
    wspec = lambda shp: pl.BlockSpec(shp, lambda i: (0,) * len(shp))
    out = pl.pallas_call(
        functools.partial(_fused_kernel2d, h, g),
        grid=(n // g,),
        in_specs=[
            pl.BlockSpec((m, c), lambda i: (i, 0)),
            wspec((c, f)), wspec((1, f)), wspec((1, f)),
            wspec((9, f, f)), wspec((1, f)), wspec((1, f)),
            wspec((f, c)), wspec((1, c)), wspec((1, c)),
        ],
        out_specs=pl.BlockSpec((m, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n * h * h, c), jnp.bfloat16),
        scratch_shapes=[pltpu.VMEM((m + 2 * (h + 1), f), jnp.bfloat16)],
        compiler_params=pltpu.CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
    )(x2, params["w1"], params["s1"], params["b1"], params["w2"],
      params["s2"], params["b2"], params["w3"], params["s3"], params["b3"])
    return out.reshape(n, h, h, c)


# ---------------------------------------------------------------------------
# XLA reference (identical math)
# ---------------------------------------------------------------------------

def xla_block(x, params):
    f = params["w1"].shape[1]

    def affine(y, s, b):
        return y * s.reshape(1, 1, 1, -1) + b.reshape(1, 1, 1, -1)

    y = jnp.einsum("nhwc,cf->nhwf", x, params["w1"],
                   preferred_element_type=jnp.float32)
    y = jnp.maximum(affine(y, params["s1"], params["b1"]), 0.0) \
        .astype(jnp.bfloat16)
    w2 = params["w2"].reshape(3, 3, f, f)
    y = lax.conv_general_dilated(
        y, w2, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    y = jnp.maximum(affine(y, params["s2"], params["b2"]), 0.0) \
        .astype(jnp.bfloat16)
    y = jnp.einsum("nhwf,fc->nhwc", y, params["w3"],
                   preferred_element_type=jnp.float32)
    y = affine(y, params["s3"], params["b3"])
    return jnp.maximum(y + x.astype(jnp.float32), 0.0).astype(jnp.bfloat16)


def xla_block_conv(x, params):
    """Same math, but 1x1 convs lowered via conv_general_dilated — the
    way a framework emitting conv ops (ours included) hits XLA."""
    f = params["w1"].shape[1]

    def affine(y, s, b):
        return y * s.reshape(1, 1, 1, -1) + b.reshape(1, 1, 1, -1)

    def conv(y, w, kh):
        return lax.conv_general_dilated(
            y, w.reshape(kh, kh, w.shape[-2], w.shape[-1]), (1, 1),
            "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32)

    y = conv(x, params["w1"][None, None], 1)
    y = jnp.maximum(affine(y, params["s1"], params["b1"]), 0.0) \
        .astype(jnp.bfloat16)
    y = conv(y, params["w2"].reshape(3, 3, f, f), 3)
    y = jnp.maximum(affine(y, params["s2"], params["b2"]), 0.0) \
        .astype(jnp.bfloat16)
    y = conv(y, params["w3"][None, None], 1)
    y = affine(y, params["s3"], params["b3"])
    return jnp.maximum(y + x.astype(jnp.float32), 0.0).astype(jnp.bfloat16)


def make_params(key, c, f):
    ks = jax.random.split(key, 3)
    sc = lambda k, shp, s: (jax.random.normal(k, shp, jnp.float32) * s
                            ).astype(jnp.bfloat16)
    return {
        "w1": sc(ks[0], (c, f), (2.0 / c) ** 0.5),
        "w2": sc(ks[1], (9, f, f), (2.0 / (9 * f)) ** 0.5),
        "w3": sc(ks[2], (f, c), (2.0 / f) ** 0.5),
        "s1": jnp.full((1, f), 1.0), "b1": jnp.zeros((1, f)),
        "s2": jnp.full((1, f), 0.5), "b2": jnp.zeros((1, f)),
        "s3": jnp.full((1, c), 0.3), "b3": jnp.zeros((1, c)),
    }


def bench(fn, x, params, k, label, flops):
    """Two-point (slope) timing: the axon tunnel adds a noisy ~100 ms
    fixed cost per launch+sync, so per-iteration time is the SLOPE
    between chains of k and 5k iterations — the fixed cost cancels.
    (Round-3 probes divided one chain's wall time by k; at millisecond
    block times that buried the signal under RTT/k — see ROUND4_NOTES.)"""
    def chain_t(iters, reps=3):
        @jax.jit
        def chain(x):
            def body(y, _):
                return fn(y, params), None
            y, _ = lax.scan(body, x, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))

        float(chain(x))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(x))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chain_t(k)
    t2 = chain_t(5 * k)
    per = (t2 - t1) / (4 * k)
    eff = flops / per / V5E_PEAK_BF16
    print(json.dumps({"path": label, "ms": round(per * 1e3, 3),
                      "frac_of_peak": round(eff, 4)}), flush=True)
    return per


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="s2", choices=list(STAGES))
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--g", type=int, default=0, help="imgs/cell (0=sweep)")
    ap.add_argument("--k", type=int, default=20)
    ap.add_argument("--check", action="store_true")
    args = ap.parse_args()

    h, c, f = STAGES[args.stage]
    n = args.batch
    flops = n * block_flops(h, c, f)
    print(json.dumps({"stage": args.stage, "h": h, "c": c, "f": f,
                      "batch": n, "gflops_per_call": round(flops / 1e9, 1)}),
          flush=True)
    params = make_params(jax.random.key(0), c, f)
    x = (jax.random.normal(jax.random.key(1), (n, h, h, c), jnp.float32)
         * 0.5).astype(jnp.bfloat16)

    if args.check:
        ref = xla_block(x[:8], params)
        for label, fn in (("4d", fused_block), ("2d", fused_block2d)):
            out = fn(x[:8], params, 4)
            err = float(jnp.max(jnp.abs(out.astype(jnp.float32)
                                        - ref.astype(jnp.float32))))
            rel = err / float(jnp.max(jnp.abs(ref.astype(jnp.float32))))
            print(json.dumps({"check": label, "max_abs_err": err,
                              "rel": round(rel, 5)}), flush=True)

    t_xla = bench(lambda y, p: xla_block(y, p), x, params, args.k,
                  "xla_dot", flops)
    bench(lambda y, p: xla_block_conv(y, p), x, params, args.k,
          "xla_conv", flops)
    gs = [args.g] if args.g else [2, 4, 8, 16]
    for label, fn in (("2d", fused_block2d), ("4d", fused_block)):
        for g in gs:
            if n % g:
                continue
            try:
                t = bench(lambda y, p, g=g, fn=fn: fn(y, p, g), x, params,
                          args.k, f"pallas{label}_g{g}", flops)
                print(json.dumps({"variant": label, "g": g,
                                  "speedup_vs_xla": round(t_xla / t, 3)}),
                      flush=True)
            except Exception as e:
                print(json.dumps({"variant": label, "g": g,
                                  "error": str(e)[:160]}), flush=True)


if __name__ == "__main__":
    main()
