#!/usr/bin/env python
"""benchdiff: CI gate over the bench trajectory (ISSUE 11 satellite).

Compares a fresh ``bench.py`` row set against the authoritative
BENCH_ALL.json and exits non-zero on a >10% regression on any matching
platform-suffixed key — the trajectory was previously eyeballed; this
makes it a gate.

Usage::

    python bench.py --only word2vec,serving_latency   # merges fresh rows
    python tools/benchdiff.py fresh.json              # fresh vs BENCH_ALL.json
    python tools/benchdiff.py fresh.json --base BENCH_ALL.json --threshold 0.1

``fresh.json`` is either a BENCH_ALL-style map (already platform-
suffixed) or a raw ``{name: row}`` result map; raw keys are normalized
exactly the way ``bench._merge_bench_all`` does it (a row measured on a
non-TPU backend lands under ``<name>_<platform>``), so a CPU run never
gates against a chip row. Direction comes from the row itself: rows in
%, ms, or seconds (overhead, latency, stall fractions) regress UP;
throughput rows (images/sec, tokens/sec, steps/s) regress DOWN.

Rows tagged ``"host_bound": true`` (serving_load_cpu, precision_cpu,
decode_cpu, coldstart_cpu — values that measure host capacity, not
model math) are reported but never gated when their platform is not
the chip they were written for: two different (or differently loaded)
hosts produce deltas that are not code regressions.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASE = os.path.join(ROOT, "BENCH_ALL.json")

_LOWER_IS_BETTER_UNITS = {"%", "ms", "s", "seconds", "ratio"}
_LOWER_IS_BETTER_HINTS = ("overhead", "latency", "stall", "_ms", "_pct",
                          "_seconds", "wait", "ratio")


def lower_is_better(row) -> bool:
    unit = str(row.get("unit", "")).lower()
    metric = str(row.get("metric", "")).lower()
    if unit in _LOWER_IS_BETTER_UNITS:
        return True
    # "x (bf16/fp32 step time; <1 is a speedup)"-style ratio units
    if unit == "x" or unit.startswith("x "):
        return True
    return any(h in metric for h in _LOWER_IS_BETTER_HINTS)


def normalize_keys(rows: dict) -> dict:
    """Apply bench._merge_bench_all's platform-suffix convention to a
    raw {name: row} result map (idempotent on already-suffixed keys)."""
    out = {}
    for key, row in rows.items():
        if not isinstance(row, dict):
            continue
        platform = str(row.get("platform", "tpu"))
        if platform != "tpu" and not key.endswith(f"_{platform}"):
            key = f"{key}_{platform}"
        out[key] = row
    return out


def compare(fresh: dict, base: dict, threshold: float = 0.10) -> list:
    """[{key, old, new, change_pct, regression}] for every key present
    in both row sets with a numeric ``value``. ``change_pct`` is signed
    so that POSITIVE means worse (direction-normalized); ``regression``
    marks relative changes past the threshold — except percent-unit
    rows (overhead acceptances measured near zero), which gate on one
    absolute percentage point and report ``change_pct`` in points."""
    fresh = normalize_keys(fresh)
    out = []
    for key in sorted(set(fresh) & set(base)):
        new_row, old_row = fresh[key], base[key]
        if not isinstance(old_row, dict):
            continue
        new_v, old_v = new_row.get("value"), old_row.get("value")
        if not isinstance(new_v, (int, float)) or \
                not isinstance(old_v, (int, float)):
            continue
        if str(old_row.get("unit", "")) != "%" and not old_v:
            continue   # relative change against zero is undefined
        if str(old_row.get("unit", "")) == "%":
            # overhead/acceptance rows measure near (or at) zero, where
            # relative change is pure noise (a 0.2% -> 0.5% drift is
            # "+150%"): percent-unit rows gate on direction-normalized
            # absolute percentage POINTS instead, one point = the
            # standard <=1% acceptance band these rows carry
            worse = (new_v - old_v) if lower_is_better(old_row) \
                else (old_v - new_v)
            regression = worse > 1.0
        else:
            worse = (new_v - old_v) / abs(old_v)
            if not lower_is_better(old_row):
                worse = -worse
            worse = 100.0 * worse
            regression = worse > 100.0 * threshold
        # host-bound rows off their intended chip (ISSUE 13 satellite):
        # the value measures host capacity (cores, scheduler, fs), so a
        # delta between two different/loaded hosts is not a code
        # regression — report the drift, never gate on it. On-chip rows
        # (platform == "tpu") always gate.
        host_bound = bool(old_row.get("host_bound")
                          or new_row.get("host_bound"))
        platform = str(new_row.get("platform",
                                   old_row.get("platform", "tpu")))
        gated = not (host_bound and platform != "tpu")
        out.append({
            "key": key,
            "old": old_v,
            "new": new_v,
            "unit": old_row.get("unit"),
            "change_pct": round(worse, 2),
            "regression": regression and gated,
            "gated": gated,
        })
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fresh", help="fresh bench rows (JSON map)")
    ap.add_argument("--base", default=DEFAULT_BASE,
                    help="baseline row set (default: BENCH_ALL.json)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="regression gate as a fraction (default 0.10)")
    args = ap.parse_args(argv)
    with open(args.fresh) as f:
        fresh = json.load(f)
    with open(args.base) as f:
        base = json.load(f)
    rows = compare(fresh, base, threshold=args.threshold)
    if not rows:
        print("benchdiff: no matching keys between fresh rows and "
              f"{os.path.basename(args.base)} — nothing gated")
        return 0
    regressions = [r for r in rows if r["regression"]]
    for r in rows:
        tag = ("REGRESSION" if r["regression"]
               else "host-bound" if not r.get("gated", True) else "ok")
        kind = "points" if r["unit"] == "%" else "%"
        print(f"[{tag:>10}] {r['key']}: {r['old']} -> {r['new']} "
              f"{r['unit'] or ''} ({r['change_pct']:+.1f} {kind} "
              f"direction-normalized, + is worse)")
    if regressions:
        print(f"benchdiff: {len(regressions)} regression(s) past "
              f"{args.threshold:.0%}", file=sys.stderr)
        return 1
    print(f"benchdiff: {len(rows)} matching row(s), none past "
          f"{args.threshold:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
