#!/usr/bin/env python
"""memreport: the offline HBM-ownership claims-table dump (ISSUE 14).

Two modes (the hloaudit.py pattern — a standalone CLI over the same
telemetry subsystem the runtime exports):

- ``--url http://host:port`` scrapes a live server's
  ``GET /debug/memory`` and pretty-prints the reconciled table;
- default: builds a small train + serve + decode workload IN PROCESS
  (a dense net fit, a warmed bucket ladder, a paged decode engine),
  so every shipped registrar category has a live claim, then prints
  the claims table, the per-device claimed-vs-in-use reconciliation
  (with the ``unattributed`` residual), and the planner's headroom
  view.

Usage::

    python tools/memreport.py
    python tools/memreport.py --url http://127.0.0.1:9000
    python tools/memreport.py --json out.json

Nothing here touches a training/serving hot path: the demo workload is
unit-scale and the census is the same scrape-time reconciliation the
/metrics handler runs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _fmt_bytes(n):
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return (f"{n:.0f} {unit}" if unit == "B"
                    else f"{n / 1.0:.1f} {unit}")
        n /= 1024.0
    return f"{n:.1f} TiB"


def fetch(url: str) -> dict:
    from urllib.request import urlopen

    with urlopen(url.rstrip("/") + "/debug/memory", timeout=10) as r:
        return json.loads(r.read().decode())


def build_demo() -> dict:
    """Exercise every shipped registrar, then census (in process)."""
    import numpy as np

    from deeplearning4j_tpu.nn.conf.configuration import (
        NeuralNetConfiguration)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.serving import InferenceSession
    from deeplearning4j_tpu.serving.decode import (DecodeEngine,
                                                   TransformerDecodeModel)
    from deeplearning4j_tpu.telemetry import memledger

    conf = (NeuralNetConfiguration.Builder().seed(7).list()
            .layer(DenseLayer.Builder().nIn(32).nOut(64).build())
            .layer(OutputLayer.Builder().nIn(64).nOut(8).build())
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    rng = np.random.RandomState(0)
    X = rng.randn(16, 32).astype(np.float32)
    y = np.eye(8, dtype=np.float32)[rng.randint(0, 8, 16)]
    net.fit([(X, y)], 2)                      # -> train claim

    session = InferenceSession()
    session.register("memreport", net, example_shape=(32,),
                     ladder=[1, 8], warmup=True)   # -> executable claims

    model = TransformerDecodeModel.init(
        vocab=64, hidden=32, n_layers=2, n_heads=2, max_len=64,
        max_slots=4, page=8, max_pages_per_slot=4)
    engine = DecodeEngine(model, name="memreport")  # -> kv_cache claim
    engine.warmup()

    snap = memledger.describe()
    engine.close()
    session.close()
    return snap


def render(snap: dict) -> str:
    lines = ["HBM ownership ledger", "=" * 64]
    claims = snap.get("claims", [])
    if not claims:
        lines.append("(no live claims)")
    else:
        w = max(len(f"{c['category']}/{c['name']}") for c in claims)
        for c in claims:
            key = f"{c['category']}/{c['name']}"
            lines.append(f"  {key:<{w}}  {_fmt_bytes(c['bytes']):>12}"
                         f"  on {c['device']}")
    lines.append("")
    lines.append("per-device reconciliation")
    lines.append("-" * 64)
    for dev, row in sorted(snap.get("devices", {}).items()):
        lines.append(f"  {dev} (source: {row.get('source', '?')})")
        for cat, b in sorted(row.get("claimed", {}).items(),
                             key=lambda kv: -kv[1]):
            lines.append(f"    {cat:<14} {_fmt_bytes(b):>12}")
        if row.get("in_use") is not None:
            lines.append(f"    {'in_use':<14} "
                         f"{_fmt_bytes(row['in_use']):>12}")
        if row.get("unattributed") is not None:
            lines.append(f"    {'unattributed':<14} "
                         f"{_fmt_bytes(row['unattributed']):>12}")
        if row.get("limit"):
            lines.append(f"    {'limit':<14} "
                         f"{_fmt_bytes(row['limit']):>12}")
    lines.append("")
    lines.append(f"planner headroom: "
                 f"{_fmt_bytes(snap.get('headroom_bytes'))}"
                 f"  (budget {_fmt_bytes(snap.get('budget_bytes'))},"
                 f" degrade floor "
                 f"{_fmt_bytes(snap.get('min_headroom_bytes'))})")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="scrape a live /debug/memory instead "
                                  "of building the in-process demo")
    ap.add_argument("--json", dest="json_out",
                    help="also write the raw census JSON here")
    args = ap.parse_args(argv)
    snap = fetch(args.url) if args.url else build_demo()
    print(render(snap))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(snap, f, indent=2)
        print(f"\nraw census written to {args.json_out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
