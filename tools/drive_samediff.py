"""Manual verify drive: SameDiff end-to-end on the real TPU (run from /root/repo)."""
import sys, os; sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax, jax.numpy as jnp
from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.optimize import Adam
print("devices:", jax.devices())
rng = np.random.RandomState(0)
X = rng.randn(256, 10).astype(np.float32)
Y = np.eye(3)[(X.sum(1) > 0).astype(int) + (X[:,0] > 1).astype(int)].astype(np.float32)
sd = SameDiff.create()
x = sd.placeHolder("x", jnp.float32, -1, 10)
y = sd.placeHolder("y", jnp.float32, -1, 3)
w1 = sd.var("w1", (0.3*rng.randn(10, 32)).astype(np.float32))
b1 = sd.var("b1", np.zeros(32, np.float32))
w2 = sd.var("w2", (0.3*rng.randn(32, 3)).astype(np.float32))
b2 = sd.var("b2", np.zeros(3, np.float32))
h = sd.nn.relu(sd.nn.linear(x, w1, b1))
logits = sd.nn.linear(h, w2, b2).rename("logits")
sd.loss.softmaxCrossEntropy(logits, y).rename("loss")
sd.setTrainingConfig(TrainingConfig(updater=Adam(0.01),
    dataSetFeatureMapping=["x"], dataSetLabelMapping=["y"], lossVariables=["loss"]))
hist = sd.fit([(X, Y)], epochs=100)
print(f"loss: {hist.lossCurve[0]:.4f} -> {hist.lossCurve[-1]:.4f}")
assert hist.lossCurve[-1] < 0.3 * hist.lossCurve[0]
preds = sd.output({"x": X}, "logits")["logits"].toNumpy()
acc = (preds.argmax(1) == Y.argmax(1)).mean()
print("train accuracy:", acc); assert acc > 0.9
sd.save("/tmp/sd_model.zip", saveUpdaterState=True)
sd2 = SameDiff.load("/tmp/sd_model.zip", loadUpdaterState=True)
np.testing.assert_allclose(preds, sd2.output({"x": X}, "logits")["logits"].toNumpy(), rtol=1e-4, atol=1e-5)
h2 = sd2.fit([(X, Y)], epochs=5)
print("resumed losses:", [round(l,4) for l in h2.lossCurve])
print("ALL SD DRIVE CHECKS PASSED")
