"""Pair-generation decomposition probe (r5, VERDICT item 1).

The r4 bench: ~4.4 s/epoch of device pair-gen for a 10M-word corpus.
Ablations on the real chip:

  full          production gen (windows + validity + cumsum + 2 scatters)
  no_compact    same but returns the uncompacted (cent, ctx, valid)
  searchsorted  scatter-free compaction: destination offsets are the
                cumsum of per-position pair counts (2b each), so output
                slot o maps back to its position by binary search and to
                its context by rank decode — all gathers, no scatter

Run: python tools/probe_w2v_pairgen.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

W = 5
P = 8_388_608          # ~8.4M positions (10M words post-subsample)
CAP2_MARGIN = 1.03


def _force(r):
    """Materialize on host: axon's block_until_ready returns before the
    remote compute lands, so reduce-and-float every output (the same
    reason bench.py uses slope timing)."""
    return sum(float(jnp.sum(jnp.ravel(x).astype(jnp.float32)[:1]))
               for x in jax.tree_util.tree_leaves(r))


def timeit(fn, *args, reps=3):
    r = fn(*args)
    _force(r)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        r = fn(*args)
        _force(r)
        best = min(best, time.perf_counter() - t0)
    return best, r


def gen_full(flat, sid, key):
    p = flat.shape[0]
    pos = jnp.arange(p, dtype=jnp.int32)
    b = jax.random.randint(key, (p,), 1, W + 1)
    cents, ctxs, vals = [], [], []
    for d in (*range(-W, 0), *range(1, W + 1)):
        j = jnp.clip(pos + d, 0, p - 1)
        valid = ((sid >= 0) & (sid[j] == sid) & (jnp.abs(d) <= b)
                 & (pos + d >= 0) & (pos + d < p))
        cents.append(flat)
        ctxs.append(flat[j])
        vals.append(valid)
    cent_s = jnp.stack(cents, 1).reshape(-1)
    ctx_s = jnp.stack(ctxs, 1).reshape(-1)
    val_s = jnp.stack(vals, 1).reshape(-1)
    cap = cent_s.shape[0]
    csum = jnp.cumsum(val_s.astype(jnp.int32))
    n_real = csum[-1]
    dest = jnp.where(val_s, csum - 1, cap + jnp.arange(cap))
    out_c = jnp.zeros((cap,), jnp.int32).at[dest].set(
        cent_s, mode="drop", unique_indices=True)
    out_x = jnp.zeros((cap,), jnp.int32).at[dest].set(
        ctx_s, mode="drop", unique_indices=True)
    return out_c, out_x, n_real


def gen_no_compact(flat, sid, key):
    p = flat.shape[0]
    pos = jnp.arange(p, dtype=jnp.int32)
    b = jax.random.randint(key, (p,), 1, W + 1)
    cents, ctxs, vals = [], [], []
    for d in (*range(-W, 0), *range(1, W + 1)):
        j = jnp.clip(pos + d, 0, p - 1)
        valid = ((sid >= 0) & (sid[j] == sid) & (jnp.abs(d) <= b)
                 & (pos + d >= 0) & (pos + d < p))
        cents.append(flat)
        ctxs.append(flat[j])
        vals.append(valid)
    cent_s = jnp.stack(cents, 1).reshape(-1)
    ctx_s = jnp.stack(ctxs, 1).reshape(-1)
    val_s = jnp.stack(vals, 1).reshape(-1)
    return cent_s, ctx_s, val_s.astype(jnp.float32)


def gen_searchsorted(flat, sid, key):
    """Scatter-free: per-position pair count is known analytically
    (only window clipping / sentence edges / corpus edges reduce it),
    so compute counts per position, cumsum, then map output slots back
    with searchsorted + rank decode. All gathers."""
    p = flat.shape[0]
    pos = jnp.arange(p, dtype=jnp.int32)
    b = jax.random.randint(key, (p,), 1, W + 1)
    # count valid contexts per position (vector math, no 2W stack)
    cnt = jnp.zeros((p,), jnp.int32)
    for d in (*range(-W, 0), *range(1, W + 1)):
        j = jnp.clip(pos + d, 0, p - 1)
        valid = ((sid >= 0) & (sid[j] == sid) & (jnp.abs(d) <= b)
                 & (pos + d >= 0) & (pos + d < p))
        cnt = cnt + valid.astype(jnp.int32)
    offs = jnp.cumsum(cnt)              # offs[i] = end of pos i's run
    n_real = offs[-1]
    cap2 = int(P * (W + 1) * CAP2_MARGIN)
    o = jnp.arange(cap2, dtype=jnp.int32)
    src = jnp.searchsorted(offs, o, side="right").astype(jnp.int32)
    src = jnp.minimum(src, p - 1)
    start = offs[src] - cnt[src]
    rank = o - start                    # 0.. cnt[src]-1
    # decode rank -> d: valid d ascending. With per-side truncation:
    # left side has L = min(b, how far left we can go) entries
    sent_ok = sid[src] >= 0
    left_room = jnp.stack(
        [((sid[jnp.clip(src - k, 0, p - 1)] == sid[src])
          & (src - k >= 0) & (k <= b[src])).astype(jnp.int32)
         for k in range(1, W + 1)], 1).sum(1)
    d_off = rank - left_room
    d = jnp.where(d_off < 0, d_off, d_off + 1)
    j = jnp.clip(src + d, 0, p - 1)
    w = ((o < n_real) & sent_ok).astype(jnp.float32)
    return flat[src], flat[j] * (w > 0), w


def gen_direct(flat, sid, key):
    """Position-major slot order identical to gen_full, but cent/ctx/
    valid computed by direct slot-index math (gathers) instead of
    stacking 2W shifted copies — no transposed [P, 2W] interleave
    writes."""
    p = flat.shape[0]
    b = jax.random.randint(key, (p,), 1, W + 1)
    cap = p * 2 * W
    s = jnp.arange(cap, dtype=jnp.int32)
    pos = s // (2 * W)
    di = s % (2 * W)
    d = jnp.where(di < W, di - W, di - W + 1)
    tgt = pos + d
    j = jnp.clip(tgt, 0, p - 1)
    sp = sid[pos]
    valid = ((sp >= 0) & (sid[j] == sp) & (jnp.abs(d) <= b[pos])
             & (tgt >= 0) & (tgt < p))
    cent_s = flat[pos]
    ctx_s = flat[j]
    csum = jnp.cumsum(valid.astype(jnp.int32))
    n_real = csum[-1]
    dest = jnp.where(valid, csum - 1, cap + jnp.arange(cap))
    out_c = jnp.zeros((cap,), jnp.int32).at[dest].set(
        cent_s, mode="drop", unique_indices=True)
    out_x = jnp.zeros((cap,), jnp.int32).at[dest].set(
        ctx_s, mode="drop", unique_indices=True)
    return out_c, out_x, n_real


def gen_direct_no_compact(flat, sid, key):
    p = flat.shape[0]
    b = jax.random.randint(key, (p,), 1, W + 1)
    cap = p * 2 * W
    s = jnp.arange(cap, dtype=jnp.int32)
    pos = s // (2 * W)
    di = s % (2 * W)
    d = jnp.where(di < W, di - W, di - W + 1)
    tgt = pos + d
    j = jnp.clip(tgt, 0, p - 1)
    sp = sid[pos]
    valid = ((sp >= 0) & (sid[j] == sp) & (jnp.abs(d) <= b[pos])
             & (tgt >= 0) & (tgt < p))
    return flat[pos], flat[j], valid.astype(jnp.float32)


def _shift(a, d, fill_edge=True):
    """a shifted by d with edge-clamp semantics (== a[clip(pos+d)]),
    expressed as slice+concat: TPU scalar gathers run at ~0.19 GB/s on
    this chip (measured above), slices at full bandwidth."""
    p = a.shape[0]
    if d == 0:
        return a
    if d > 0:
        edge = jnp.broadcast_to(a[-1:], (d,)) if fill_edge else \
            jnp.zeros((d,), a.dtype)
        return jnp.concatenate([a[d:], edge])
    edge = jnp.broadcast_to(a[:1], (-d,)) if fill_edge else \
        jnp.zeros((-d,), a.dtype)
    return jnp.concatenate([edge, a[:d]])


def gen_slices_rowscatter(flat, sid, key):
    """Slice-based shifts + ONE [cap, 2] row-scatter compaction (cent
    and ctx ride one scatter as a 2-wide row; no x64 needed)."""
    p = flat.shape[0]
    pos = jnp.arange(p, dtype=jnp.int32)
    b = jax.random.randint(key, (p,), 1, W + 1)
    cents, ctxs, vals = [], [], []
    for d in (*range(-W, 0), *range(1, W + 1)):
        valid = ((sid >= 0) & (_shift(sid, d) == sid)
                 & (jnp.abs(d) <= b)
                 & (pos + d >= 0) & (pos + d < p))
        cents.append(flat)
        ctxs.append(_shift(flat, d))
        vals.append(valid)
    cent_s = jnp.stack(cents, 1).reshape(-1)
    ctx_s = jnp.stack(ctxs, 1).reshape(-1)
    val_s = jnp.stack(vals, 1).reshape(-1)
    cap = cent_s.shape[0]
    rows = jnp.stack([cent_s, ctx_s], 1)           # [cap, 2]
    csum = jnp.cumsum(val_s.astype(jnp.int32))
    n_real = csum[-1]
    dest = jnp.where(val_s, csum - 1, cap + jnp.arange(cap))
    out = jnp.zeros((cap, 2), jnp.int32).at[dest].set(
        rows, mode="drop", unique_indices=True)
    return out[:, 0], out[:, 1], n_real


def gen_slices_two_scatter(flat, sid, key):
    """Slice-based shifts, original two int32 scatters."""
    p = flat.shape[0]
    pos = jnp.arange(p, dtype=jnp.int32)
    b = jax.random.randint(key, (p,), 1, W + 1)
    cents, ctxs, vals = [], [], []
    for d in (*range(-W, 0), *range(1, W + 1)):
        valid = ((sid >= 0) & (_shift(sid, d) == sid)
                 & (jnp.abs(d) <= b)
                 & (pos + d >= 0) & (pos + d < p))
        cents.append(flat)
        ctxs.append(_shift(flat, d))
        vals.append(valid)
    cent_s = jnp.stack(cents, 1).reshape(-1)
    ctx_s = jnp.stack(ctxs, 1).reshape(-1)
    val_s = jnp.stack(vals, 1).reshape(-1)
    cap = cent_s.shape[0]
    csum = jnp.cumsum(val_s.astype(jnp.int32))
    n_real = csum[-1]
    dest = jnp.where(val_s, csum - 1, cap + jnp.arange(cap))
    out_c = jnp.zeros((cap,), jnp.int32).at[dest].set(
        cent_s, mode="drop", unique_indices=True)
    out_x = jnp.zeros((cap,), jnp.int32).at[dest].set(
        ctx_s, mode="drop", unique_indices=True)
    return out_c, out_x, n_real


def main():
    rng = np.random.default_rng(0)
    print(json.dumps({"P": P, "W": W,
                      "device": str(jax.devices()[0])}), flush=True)
    sent_len = 25
    flat = rng.integers(0, 100_000, P).astype(np.int32)
    sid = np.repeat(np.arange(P // sent_len + 1, dtype=np.int32),
                    sent_len)[:P]
    flat_d = jax.device_put(flat)
    sid_d = jax.device_put(sid)
    key = jax.random.key(3, impl="rbg")

    for name, fn in (("full", gen_full),
                     ("no_compact", gen_no_compact),
                     ("direct", gen_direct),
                     ("direct_no_compact", gen_direct_no_compact),
                     ("searchsorted", gen_searchsorted)):
        t, r = timeit(jax.jit(fn), flat_d, sid_d, key)
        print(json.dumps({"variant": name, "s": round(t, 3),
                          "words_per_s_M": round(P / t / 1e6, 1)}),
              flush=True)
        if name == "searchsorted":
            # parity vs full: same pair MULTISET per position prefix
            c_f, x_f, n_f = jax.jit(gen_full)(flat_d, sid_d, key)
            c_s, x_s, w_s = r
            n_s = int(np.asarray(w_s, np.int64).sum())
            print(json.dumps({"pairs_full": int(n_f),
                              "pairs_ss": n_s}), flush=True)
            a = np.stack([np.asarray(c_f[:int(n_f)]),
                          np.asarray(x_f[:int(n_f)])], 1)
            mask = np.asarray(w_s) > 0
            bq = np.stack([np.asarray(c_s)[mask],
                           np.asarray(x_s)[mask]], 1)
            same = (a.shape == bq.shape) and bool(
                (np.sort(a.view("i8").ravel())
                 == np.sort(bq.view("i8").ravel())).all())
            print(json.dumps({"pair_multiset_equal": same}), flush=True)


if __name__ == "__main__":
    main()
