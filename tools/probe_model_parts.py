"""Slope-timed decomposition of the ResNet-50 b256 train step.

probe_block_train r4: identity bottleneck blocks run at 53-62% of peak
in train mode, yet the full model measures ~16-17% MFU — a 3x gap that
RESNET_MFU.md (r3) mis-attributed to a per-conv XLA ceiling on polluted
timing. This probe cumulatively truncates the hand-written model
(probe_resnet.make_forward) and slope-times each prefix's TRAIN step,
so the per-segment deltas say where the ~98 ms actually goes.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import probe_resnet as pr

V5E_PEAK_BF16 = 197e12


def slope_time(step_fn, args0, k1=4, reps=3, target=2.0):
    """Per-iteration time of step_fn via two-span slope (RTT cancels)."""
    def chain_t(iters):
        @jax.jit
        def chain(a):
            def body(carry, _):
                return step_fn(carry), None
            c, _ = lax.scan(body, a, None, length=iters)
            return jax.tree_util.tree_reduce(
                lambda s, t: s + jnp.sum(t[..., :1].astype(jnp.float32)),
                c, 0.0)

        float(chain(args0))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(args0))
            best = min(best, time.perf_counter() - t0)
        return best

    t_probe = chain_t(k1)
    per0 = max(t_probe / k1, 1e-4)
    k_long = max(k1, int(target / per0))
    k_short = max(1, k_long // 5)
    t1 = chain_t(k_short)
    t2 = chain_t(k_long)
    return (t2 - t1) / (k_long - k_short)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--bn", default="onepass")
    ap.add_argument("--stem", default="conv", choices=["conv", "s2d"])
    args = ap.parse_args()
    b = args.batch
    rng = np.random.default_rng(0)
    params = pr.init_params(jax.random.key(0))
    x = jnp.asarray(rng.normal(size=(b, 224, 224, 3)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (b,)), jnp.int32)
    full_stages = list(pr.STAGES)

    def train_step_factory(fwd, head):
        def step(carry):
            params, xx = carry

            def loss_fn(p):
                out = fwd(p, xx)
                if head:
                    lp = jax.nn.log_softmax(out)
                    return -jnp.mean(jnp.take_along_axis(
                        lp, labels[:, None], 1))
                return jnp.mean(jnp.square(out.astype(jnp.float32)))

            l, g = jax.value_and_grad(loss_fn)(params)
            params = jax.tree_util.tree_map(
                lambda p, gg: p - 1e-6 * gg.astype(p.dtype), params, g)
            return (params, xx + (l * 1e-30).astype(xx.dtype))
        return step

    prev = 0.0
    rows = []
    for upto in range(len(full_stages) + 2):
        pr.STAGES[:] = full_stages[:min(upto, len(full_stages))]
        head = upto == len(full_stages) + 1
        fwd = pr.make_forward("NHWC", args.bn, head=head, stem=args.stem)
        step = train_step_factory(fwd, head)
        per = slope_time(step, (params, x))
        name = ("stem+pool" if upto == 0 else
                "full+head" if head else f"+stage{upto - 1}")
        rows.append({"prefix": name,
                     "cum_ms": round(per * 1e3, 2),
                     "delta_ms": round((per - prev) * 1e3, 2)})
        print(json.dumps(rows[-1]), flush=True)
        prev = per
    pr.STAGES[:] = full_stages
    ips = b / prev
    mfu = ips * pr.TRAIN_FLOPS_PER_IMG / V5E_PEAK_BF16
    print(json.dumps({"img_per_sec": round(ips, 1),
                      "mfu": round(mfu, 4)}), flush=True)


if __name__ == "__main__":
    main()
