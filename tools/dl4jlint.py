#!/usr/bin/env python
"""dl4jlint CLI: project-invariant static analysis (ISSUE 7).

Usage:
  python tools/dl4jlint.py deeplearning4j_tpu/          full run
  python tools/dl4jlint.py --changed                    lint only files
                                                        touched vs git
  python tools/dl4jlint.py --baseline-update            re-triage
  python tools/dl4jlint.py --list-rules                 rule catalog

Exit codes: 0 clean (all findings baselined/suppressed), 1 findings,
2 usage/internal error. Baseline: tools/dl4jlint_baseline.json
(committed; every entry carries a one-line reason). Inline escape
hatch: ``# dl4jlint: disable=<rule>[,<rule>]`` on the flagged line or
the enclosing def. Catalog + workflow: docs/STATIC_ANALYSIS.md.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

DEFAULT_BASELINE = os.path.join(ROOT, "tools", "dl4jlint_baseline.json")
DEFAULT_TARGET = os.path.join(ROOT, "deeplearning4j_tpu")


def changed_files() -> list:
    """Package .py files touched vs git HEAD (staged + unstaged +
    untracked) — the fast pre-commit set."""
    out = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=ROOT, capture_output=True,
                                  text=True, check=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            print(f"dl4jlint: --changed needs git ({e})",
                  file=sys.stderr)
            raise SystemExit(2)
        out.update(l.strip() for l in proc.stdout.splitlines()
                   if l.strip())
    return sorted(
        os.path.join(ROOT, f) for f in out
        if f.endswith(".py") and f.startswith("deeplearning4j_tpu/")
        and os.path.exists(os.path.join(ROOT, f)))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="dl4jlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*",
                    help="files/directories (default: the package)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report everything, ignore the baseline")
    ap.add_argument("--baseline-update", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(reasons preserved for surviving keys)")
    ap.add_argument("--changed", action="store_true",
                    help="report only findings in files changed vs git "
                         "HEAD (whole package is still parsed for "
                         "cross-module context)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--show-baselined", action="store_true",
                    help="also print findings the baseline covers")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset to run")
    args = ap.parse_args(argv)

    from deeplearning4j_tpu.analysis import (Baseline, all_rules,
                                             analyze)

    rules = all_rules()
    if args.list_rules:
        for name in sorted(rules):
            r = rules[name]
            print(f"{name:22s} [{r.severity}] {r.description}")
        return 0
    if args.rules:
        want = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = want - set(rules)
        if unknown:
            print(f"dl4jlint: unknown rules: {sorted(unknown)}",
                  file=sys.stderr)
            return 2
        rules = {k: v for k, v in rules.items() if k in want}

    paths = args.paths or [DEFAULT_TARGET]
    changed = None
    if args.changed:
        changed = changed_files()
        if not changed:
            print("dl4jlint: no changed package files")
            return 0
        paths = [DEFAULT_TARGET]  # full context, filtered report

    baseline = None if args.no_baseline else \
        Baseline.load(args.baseline)
    report = analyze(paths, root=ROOT, baseline=baseline, rules=rules)

    if args.baseline_update:
        # always rewrite FROM the committed baseline (even under
        # --no-baseline) so triage reasons survive the regeneration;
        # a --rules subset run only rewrites that subset's entries
        bl = baseline if baseline is not None \
            else Baseline.load(args.baseline)
        bl.update_from(report.all_findings,
                       restrict_to_rules=set(rules) if args.rules
                       else None)
        bl.save(args.baseline)
        print(f"dl4jlint: baseline rewritten with "
              f"{len(bl.entries)} entries -> {args.baseline}")
        return 0

    new = report.new
    if changed is not None:
        rels = {os.path.relpath(c, ROOT).replace(os.sep, "/")
                for c in changed}
        new = [f for f in new if f.file in rels]

    for f in sorted(new, key=lambda f: (f.file, f.line)):
        print(f.render())
    n_mod = len(report.project.modules)
    n_base = len(report.baselined)
    if new:
        print(f"dl4jlint: {len(new)} finding(s) over {n_mod} files "
              f"({n_base} baselined, {report.suppressed_count} "
              f"suppressed)", file=sys.stderr)
        if report.stale_keys:
            print(f"dl4jlint: note: {len(report.stale_keys)} stale "
                  f"baseline entr(ies) — run --baseline-update",
                  file=sys.stderr)
        return 1
    print(f"dl4jlint: clean — {n_mod} files, "
          f"{len(rules)} rules, {n_base} baselined, "
          f"{report.suppressed_count} suppressed"
          + (f", {len(report.stale_keys)} stale baseline entries "
             f"(run --baseline-update)" if report.stale_keys else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
