#!/usr/bin/env python
"""Metric-name drift check — thin shim over the dl4jlint metric-drift
rule (ISSUE 7 absorbed the PR-3 satellite tool into the analyzer).

The contract is unchanged: every metric registered by instrumented
code must (a) use the ``dl4j_`` prefix and (b) be documented in
docs/OBSERVABILITY.md. Run standalone (``python tools/check_metrics.py``,
exits non-zero on drift), via tests/test_health.py::TestMetricNameDrift,
or — the successor path — as the ``metric-drift`` rule inside
``python tools/dl4jlint.py``.

Kept API (used by test_health.py and docs): ``collect_metric_names()``,
``check(names=, docs_text=)``, ``main()``.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

PACKAGE = ROOT / "deeplearning4j_tpu"
DOCS = ROOT / "docs" / "OBSERVABILITY.md"


def _project():
    from deeplearning4j_tpu.analysis.model import load_project

    return load_project([str(PACKAGE)], root=str(ROOT))


def collect_metric_names() -> dict:
    """{metric_name: [files registering it]} across the package
    (AST-based, via the dl4jlint metric-drift rule collector)."""
    from deeplearning4j_tpu.analysis.rules.metric_drift import (
        collect_metric_names as collect)

    return collect(_project())


def check(names=None, docs_text=None) -> list:
    """Drift findings as human-readable strings (empty = clean)."""
    from deeplearning4j_tpu.analysis.rules.metric_drift import (
        drift_problems)

    names = collect_metric_names() if names is None else names
    docs_text = DOCS.read_text() if docs_text is None else docs_text
    return drift_problems(names, docs_text)


def main() -> int:
    names = collect_metric_names()
    problems = check(names)
    print(f"checked {len(names)} registered metric names")
    for p in problems:
        print(f"DRIFT: {p}", file=sys.stderr)
    if problems:
        return 1
    print("no metric-name drift")
    return 0


if __name__ == "__main__":
    sys.exit(main())
