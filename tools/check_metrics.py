#!/usr/bin/env python
"""Metric-name drift check (ISSUE 3 satellite).

Every metric registered by instrumented code must (a) use the ``dl4j_``
prefix and (b) be documented in docs/OBSERVABILITY.md — otherwise
dashboards and alert rules silently drift from the code. Run standalone
(``python tools/check_metrics.py``, exits non-zero on drift) or via
tests/test_health.py::TestMetricNameDrift.

Names are collected by scanning the package source for literal
``.counter("...")`` / ``.gauge("...")`` / ``.histogram("...")``
registrations, so a new instrument cannot be added without either
following the convention or updating this tool.
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PACKAGE = ROOT / "deeplearning4j_tpu"
DOCS = ROOT / "docs" / "OBSERVABILITY.md"

# literal first argument of a registry registration call; re.S lets the
# name sit on the line after the open paren (the prevailing style here)
_REGISTRATION = re.compile(
    r'\.\s*(?:counter|gauge|histogram)\(\s*[\'"]([A-Za-z_:][\w:]*)[\'"]',
    re.S)

# derived sample names the registry emits beside the family name — they
# need no separate doc entry
_DERIVED_SUFFIXES = ("_bucket", "_sum", "_count")


def collect_metric_names() -> dict:
    """{metric_name: [files registering it]} across the package."""
    names: dict = {}
    for path in sorted(PACKAGE.rglob("*.py")):
        text = path.read_text()
        for name in _REGISTRATION.findall(text):
            names.setdefault(name, []).append(
                str(path.relative_to(ROOT)))
    return names


def check(names=None, docs_text=None) -> list:
    """Drift findings as human-readable strings (empty = clean)."""
    names = collect_metric_names() if names is None else names
    docs_text = DOCS.read_text() if docs_text is None else docs_text
    problems = []
    for name, files in sorted(names.items()):
        where = ", ".join(sorted(set(files)))
        if not name.startswith("dl4j_"):
            problems.append(
                f"metric {name!r} ({where}) does not use the dl4j_ "
                f"prefix")
        # whole-name match: plain substring would let `dl4j_step` hide
        # behind a documented `dl4j_step_seconds`
        if not re.search(re.escape(name) + r"(?![\w])", docs_text):
            problems.append(
                f"metric {name!r} ({where}) is not documented in "
                f"docs/OBSERVABILITY.md")
    return problems


def main() -> int:
    names = collect_metric_names()
    problems = check(names)
    print(f"checked {len(names)} registered metric names")
    for p in problems:
        print(f"DRIFT: {p}", file=sys.stderr)
    if problems:
        return 1
    print("no metric-name drift")
    return 0


if __name__ == "__main__":
    sys.exit(main())
