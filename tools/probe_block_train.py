"""Train-mode (fwd+bwd) bottleneck-block probe with slope timing.

probe_fused_block r4 found the FORWARD XLA block at ~96% of peak once
the ~100ms axon-tunnel RTT is slope-cancelled — so ResNet-50's measured
~16% training MFU is NOT a per-block conv ceiling. This probe bisects
training: fwd-only vs fwd+bwd, affine-BN vs one-pass batch-stats BN,
with/without residual, at each stage shape.

Chaining keeps a serial dependence through BOTH x-grads and param-grads
so nothing is DCE'd or hoisted.
"""
from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V5E_PEAK_BF16 = 197e12
STAGES = {"s0": (56, 256, 64), "s1": (28, 512, 128),
          "s2": (14, 1024, 256), "s3": (7, 2048, 512)}


def make_block(bn_mode, residual=True):
    def affine(y, s, b):
        return y * s.reshape(1, 1, 1, -1) + b.reshape(1, 1, 1, -1)

    def bn(y, s, b):
        if bn_mode == "affine":
            return affine(y.astype(jnp.float32), s, b)
        yf = y.astype(jnp.float32)
        mean = jnp.mean(yf, axis=(0, 1, 2))
        var = jnp.mean(jnp.square(yf), axis=(0, 1, 2)) - jnp.square(mean)
        inv = lax.rsqrt(var + 1e-5) * s
        return yf * inv.reshape(1, 1, 1, -1) + \
            (b - mean * inv).reshape(1, 1, 1, -1)

    def conv(y, w, kh):
        # pure-bf16 conv (probe_resnet's lowering): output bf16, so the
        # autodiff-transposed convs see bf16 cotangents (a f32
        # preferred_element_type output would hand the transpose a f32
        # cotangent conv_general_dilated rejects against bf16 weights)
        return lax.conv_general_dilated(
            y.astype(jnp.bfloat16),
            w.reshape(kh, kh, w.shape[-2], w.shape[-1])
            .astype(jnp.bfloat16), (1, 1),
            "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def block(params, x):
        f = params["w1"].shape[1]
        y = conv(x, params["w1"][None, None], 1)
        y = jnp.maximum(bn(y, params["s1"], params["b1"]), 0.0) \
            .astype(jnp.bfloat16)
        y = conv(y, params["w2"].reshape(3, 3, f, f), 3)
        y = jnp.maximum(bn(y, params["s2"], params["b2"]), 0.0) \
            .astype(jnp.bfloat16)
        y = conv(y, params["w3"][None, None], 1)
        y = bn(y, params["s3"], params["b3"])  # f32
        if residual:
            y = y + x.astype(jnp.float32)
        return jnp.maximum(y, 0.0).astype(jnp.bfloat16)

    return block


def make_params(key, c, f):
    ks = jax.random.split(key, 3)
    sc = lambda k, shp, s: (jax.random.normal(k, shp, jnp.float32) * s
                            ).astype(jnp.bfloat16)
    return {"w1": sc(ks[0], (c, f), (2.0 / c) ** 0.5),
            "w2": sc(ks[1], (9, f, f), (2.0 / (9 * f)) ** 0.5),
            "w3": sc(ks[2], (f, c), (2.0 / f) ** 0.5),
            "s1": jnp.full((f,), 1.0), "b1": jnp.zeros((f,)),
            "s2": jnp.full((f,), 1.0), "b2": jnp.zeros((f,)),
            "s3": jnp.full((c,), 0.3), "b3": jnp.zeros((c,))}


def slope_bench(step, x0, k1, label, flops):
    """Two-span slope timing with auto-scaling: span length grows until
    the long chain runs >=1.5 s so the ~100ms-noise tunnel RTT cannot
    swamp the slope; reports both of two independent slope estimates so
    disagreement is visible."""
    def chain_t(iters, reps=4):
        @jax.jit
        def chain(x):
            def body(y, _):
                return step(y), None
            y, _ = lax.scan(body, x, None, length=iters)
            return jnp.sum(y.astype(jnp.float32))

        float(chain(x0))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(x0))
            best = min(best, time.perf_counter() - t0)
        return best

    # rough per-iter estimate to size the spans
    t_probe = chain_t(k1, reps=2)
    per0 = max(t_probe / k1, 1e-5)
    k_long = max(k1, int(1.5 / per0))
    k_short = k_long // 5
    t1 = chain_t(k_short)
    t2 = chain_t(k_long)
    per_a = (t2 - t1) / (k_long - k_short)
    t1b = chain_t(k_short)
    t2b = chain_t(k_long)
    per_b = (t2b - t1b) / (k_long - k_short)
    per = (per_a + per_b) / 2
    print(json.dumps({"path": label, "ms": round(per * 1e3, 3),
                      "ms_b": round(max(per_a, per_b) * 1e3, 3),
                      "frac_of_peak": round(flops / per / V5E_PEAK_BF16,
                                            4)}), flush=True)
    return per


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="s2", choices=list(STAGES))
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--k", type=int, default=30)
    args = ap.parse_args()
    h, c, f = STAGES[args.stage]
    n = args.batch
    fwd_flops = n * 2 * h * h * (c * f + 9 * f * f + f * c)
    params = make_params(jax.random.key(0), c, f)
    x = (jax.random.normal(jax.random.key(1), (n, h, h, c), jnp.float32)
         * 0.5).astype(jnp.bfloat16)
    print(json.dumps({"stage": args.stage, "batch": n,
                      "fwd_gflops": round(fwd_flops / 1e9, 1)}), flush=True)

    for bn_mode in ("affine", "onepass"):
        blk = make_block(bn_mode)
        slope_bench(lambda y: blk(params, y), x, args.k,
                    f"fwd_{bn_mode}", fwd_flops)

        def train_step(y, blk=blk):
            def loss_fn(p, yy):
                return jnp.sum(blk(p, yy).astype(jnp.float32) ** 2) * 1e-6
            l, (gp, gy) = jax.value_and_grad(
                loss_fn, argnums=(0, 1))(params, y)
            tiny = sum(jnp.sum(t.astype(jnp.float32)) * 1e-30
                       for t in jax.tree_util.tree_leaves(gp))
            return (y - gy * jnp.bfloat16(1e-6)
                    + (tiny * 0 + l * 0).astype(jnp.bfloat16))

        slope_bench(train_step, x, max(args.k // 3, 10),
                    f"train_{bn_mode}", 3 * fwd_flops)


if __name__ == "__main__":
    main()
