"""Whole-model ResNet-50 training ablation probe for the MFU diagnosis.

Hand-written minimal ResNet-50 train step (pure jnp, bf16 activations,
f32 params, SGD momentum) measured at K steps per launch, ablating:
  --layout NHWC|NCHW        conv/BN data layout
  --bn twopass|onepass|none batchnorm stats strategy
  --batch N

The framework model (zoo.ResNet50 via fitMultiBatch) measures 10.9% MFU
(BENCH_ALL round 2); per-shape convs sustain 25-45% of peak
(tools/probe_conv.py), so this probe separates framework overhead from
the model's intrinsic ceiling on v5e and tells us which knobs matter.

Run: python tools/probe_resnet.py --layout NHWC --bn onepass
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V5E_PEAK_BF16 = 197e12
TRAIN_FLOPS_PER_IMG = 3 * 4.1e9

# (filters, blocks, stride) per stage
STAGES = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def _conv_init(key, cin, cout, k):
    std = float(np.sqrt(2.0 / (k * k * cin)))
    return jax.random.normal(key, (k, k, cin, cout), jnp.float32) * std


def init_params(key, num_classes=1000):
    keys = iter(jax.random.split(key, 256))
    p = {"stem": {"w": _conv_init(next(keys), 3, 64, 7),
                  "g": jnp.ones((64,)), "b": jnp.zeros((64,))}}
    cin = 64
    for si, (f, blocks, stride) in enumerate(STAGES):
        for bi in range(blocks):
            blk = {}
            s = stride if bi == 0 else 1
            cout = 4 * f
            blk["c1"] = {"w": _conv_init(next(keys), cin, f, 1),
                         "g": jnp.ones((f,)), "b": jnp.zeros((f,))}
            blk["c2"] = {"w": _conv_init(next(keys), f, f, 3),
                         "g": jnp.ones((f,)), "b": jnp.zeros((f,))}
            blk["c3"] = {"w": _conv_init(next(keys), f, cout, 1),
                         "g": jnp.ones((cout,)), "b": jnp.zeros((cout,))}
            if bi == 0:
                blk["proj"] = {"w": _conv_init(next(keys), cin, cout, 1),
                               "g": jnp.ones((cout,)),
                               "b": jnp.zeros((cout,))}
            p[f"s{si}b{bi}"] = blk
            cin = cout
    p["fc"] = {"w": jax.random.normal(next(keys), (cin, num_classes),
                                      jnp.float32) * 0.01,
               "b": jnp.zeros((num_classes,))}
    return p


def s2d_nhwc(x, b=2):
    n, h, w, c = x.shape
    x = x.reshape(n, h // b, b, w // b, b, c)
    return jnp.transpose(x, (0, 1, 3, 2, 4, 5)).reshape(
        n, h // b, w // b, b * b * c)


def stem_kernel_s2d(w):
    """[7,7,3,64] stride-2 stem kernel -> the EXACT-equivalent [4,4,12,64]
    stride-1 kernel over space-to-depth(2) input (zero-pad 7->8, fold the
    2x2 phase into channels; the MLPerf ResNet stem transform)."""
    cin, cout = w.shape[2], w.shape[3]
    w = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))   # [8,8,cin,cout]
    w = w.reshape(4, 2, 4, 2, cin, cout)
    # s2d packs (bh, bw, c) with spatial-block-major, channel-fastest:
    # in channel index = (bh*2 + bw)*C + c
    return jnp.transpose(w, (0, 2, 1, 3, 4, 5)).reshape(
        4, 4, 4 * cin, cout)


def make_forward(layout, bn_mode, head=True, stem="conv"):
    nhwc = layout == "NHWC"
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    caxis = 3 if nhwc else 1

    def conv(x, w, stride):
        if not nhwc:
            w = jnp.transpose(w, (3, 2, 0, 1))  # HWIO -> OIHW
        return lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=dn)

    def bn(x, g, b):
        axes = tuple(i for i in range(4) if i != caxis)
        shape = [1, 1, 1, 1]
        shape[caxis] = -1
        if bn_mode == "none":
            return x * g.reshape(shape).astype(x.dtype) \
                + b.reshape(shape).astype(x.dtype)
        xf = x.astype(jnp.float32)
        if bn_mode == "twopass":
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(jnp.square(xf - mean.reshape(shape)), axis=axes)
        else:  # onepass: E[x^2] - mean^2, f32 accumulation
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
        inv = lax.rsqrt(var + 1e-5) * g
        return (xf * inv.reshape(shape)
                + (b - mean * inv).reshape(shape)).astype(x.dtype)

    def cbr(x, pp, stride, relu=True):
        y = bn(conv(x, pp["w"], stride), pp["g"], pp["b"])
        return jax.nn.relu(y) if relu else y

    def forward(params, x):
        if stem == "s2d":
            if not nhwc:
                raise ValueError("s2d stem probe is NHWC-only")
            y = conv(s2d_nhwc(x), stem_kernel_s2d(params["stem"]["w"]), 1)
            y = bn(y, params["stem"]["g"], params["stem"]["b"])
            y = jax.nn.relu(y)
        else:
            y = cbr(x, params["stem"], 2)
        window = (1, 3, 3, 1) if nhwc else (1, 1, 3, 3)
        strides = (1, 2, 2, 1) if nhwc else (1, 1, 2, 2)
        y = lax.reduce_window(y, -jnp.inf, lax.max, window, strides, "SAME")
        cin = 64
        for si, (f, blocks, stride) in enumerate(STAGES):
            for bi in range(blocks):
                blk = params[f"s{si}b{bi}"]
                s = stride if bi == 0 else 1
                h = cbr(y, blk["c1"], s)
                h = cbr(h, blk["c2"], 1)
                h = cbr(h, blk["c3"], 1, relu=False)
                if bi == 0:
                    y = cbr(y, blk["proj"], s, relu=False)
                y = jax.nn.relu(y + h)
        y = jnp.mean(y.astype(jnp.float32), axis=(1, 2) if nhwc else (2, 3))
        if not head:
            return y
        return y @ params["fc"]["w"] + params["fc"]["b"]

    return forward


def stage_probe(args):
    """Cumulative-prefix timing: train-step time of the model truncated
    after each stage; successive deltas localize where the whole-model
    time goes (vs the per-shape conv numbers)."""
    nhwc = args.layout == "NHWC"
    b = args.batch
    rng = np.random.default_rng(0)
    params = init_params(jax.random.key(0))

    results = {}
    full_stages = list(STAGES)
    prev = None
    for upto in range(len(full_stages) + 1):
        STAGES[:] = full_stages[:upto]
        fwd_u = make_forward(args.layout, args.bn, head=False)

        def loss_fn(params, x, fwd_u=fwd_u):
            pooled = fwd_u(params, x)
            # scalar objective over pooled features; grads flow through
            # every used layer (fc/yet-unbuilt stages get zero grads)
            return jnp.mean(jnp.square(pooled))

        @jax.jit
        def fb(params, x):
            l, g = jax.value_and_grad(loss_fn)(params, x)
            # touch every grad leaf so nothing is dead-code eliminated
            return l + sum(jnp.max(jnp.abs(t)) * 1e-30
                           for t in jax.tree_util.tree_leaves(g))

        shape = (b, 224, 224, 3) if nhwc else (b, 3, 224, 224)
        x = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        float(fb(params, x))
        float(fb(params, x))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(fb(params, x))
            best = min(best, time.perf_counter() - t0)
        name = "stem+pool" if upto == 0 else f"+stage{upto - 1}"
        delta = best - prev if prev is not None else best
        results[name] = {"cum_ms": round(best * 1e3, 2),
                         "delta_ms": round(delta * 1e3, 2)}
        print(json.dumps({name: results[name]}), flush=True)
        prev = best
    STAGES[:] = full_stages
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layout", default="NHWC", choices=["NHWC", "NCHW"])
    ap.add_argument("--bn", default="twopass",
                    choices=["twopass", "onepass", "none"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--ksteps", type=int, default=8)
    ap.add_argument("--mode", default="train",
                    choices=["train", "stages"])
    ap.add_argument("--stem", default="conv", choices=["conv", "s2d"])
    args = ap.parse_args()
    if args.mode == "stages":
        stage_probe(args)
        return

    fwd = make_forward(args.layout, args.bn, stem=args.stem)
    params = init_params(jax.random.key(0))
    mom = jax.tree_util.tree_map(jnp.zeros_like, params)
    b = args.batch
    rng = np.random.default_rng(0)
    shape = (b, 224, 224, 3) if args.layout == "NHWC" else (b, 3, 224, 224)
    x = jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (b,)), jnp.int32)

    def loss_fn(params, x, labels):
        logits = fwd(params, x)
        lp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], 1))

    @jax.jit
    def steps(params, mom, x, labels):
        def body(carry, _):
            params, mom = carry
            loss, g = jax.value_and_grad(loss_fn)(params, x, labels)
            mom = jax.tree_util.tree_map(
                lambda m, gg: 0.9 * m + gg, mom, g)
            params = jax.tree_util.tree_map(
                lambda p, m: p - 0.01 * m, params, mom)
            return (params, mom), loss

        (params, mom), losses = lax.scan(body, (params, mom), None,
                                         length=args.ksteps)
        return params, mom, losses

    k = args.ksteps
    params, mom, losses = steps(params, mom, x, labels)
    float(losses[-1])  # compile+run
    params, mom, losses = steps(params, mom, x, labels)
    float(losses[-1])  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        params, mom, losses = steps(params, mom, x, labels)
        float(losses[-1])
        best = min(best, (time.perf_counter() - t0) / k)

    ips = b / best
    mfu = ips * TRAIN_FLOPS_PER_IMG / V5E_PEAK_BF16
    print(json.dumps({
        "layout": args.layout, "bn": args.bn, "batch": b,
        "img_per_sec": round(ips, 1), "step_ms": round(best * 1e3, 2),
        "mfu": round(mfu, 4),
    }))


if __name__ == "__main__":
    main()
