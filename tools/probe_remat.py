"""Full-model ResNet-50 b256 train-step levers, slope-timed:
  baseline     - probe_resnet fwd as-is
  remat_all    - each bottleneck block wrapped in jax.checkpoint
  remat_early  - only stages 0-1 blocks checkpointed (the HBM-bound ones)
probe_model_parts r4 localized ~60% of step time to stages 0-1 at ~16-30%
efficiency (saved-activation HBM traffic); remat trades +1/3 FLOPs for
that traffic.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import probe_resnet as pr

V5E_PEAK_BF16 = 197e12


def make_forward_remat(layout, bn_mode, remat_stages, stem="conv"):
    nhwc = layout == "NHWC"
    dn = ("NHWC", "HWIO", "NHWC") if nhwc else ("NCHW", "OIHW", "NCHW")
    caxis = 3 if nhwc else 1

    def conv(x, w, stride):
        if not nhwc:
            w = jnp.transpose(w, (3, 2, 0, 1))
        return lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=dn)

    def bn(x, g, b):
        axes = tuple(i for i in range(4) if i != caxis)
        shape = [1, 1, 1, 1]
        shape[caxis] = -1
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
        inv = lax.rsqrt(var + 1e-5) * g
        return (xf * inv.reshape(shape)
                + (b - mean * inv).reshape(shape)).astype(x.dtype)

    def cbr(x, pp, stride, relu=True):
        y = bn(conv(x, pp["w"], stride), pp["g"], pp["b"])
        return jax.nn.relu(y) if relu else y

    def block(blk, y, s, has_proj):
        h = cbr(y, blk["c1"], s)
        h = cbr(h, blk["c2"], 1)
        h = cbr(h, blk["c3"], 1, relu=False)
        if has_proj:
            y = cbr(y, blk["proj"], s, relu=False)
        return jax.nn.relu(y + h)

    def forward(params, x):
        y = cbr(x, params["stem"], 2)
        window = (1, 3, 3, 1) if nhwc else (1, 1, 3, 3)
        strides = (1, 2, 2, 1) if nhwc else (1, 1, 2, 2)
        y = lax.reduce_window(y, -jnp.inf, lax.max, window, strides,
                              "SAME")
        for si, (f, blocks, stride) in enumerate(pr.STAGES):
            for bi in range(blocks):
                blk = params[f"s{si}b{bi}"]
                s = stride if bi == 0 else 1
                fn = block
                if si in remat_stages:
                    fn = jax.checkpoint(block, static_argnums=(2, 3))
                y = fn(blk, y, s, bi == 0)
        y = jnp.mean(y.astype(jnp.float32), axis=(1, 2) if nhwc else (2, 3))
        return y @ params["fc"]["w"] + params["fc"]["b"]

    return forward


def slope_time(step_fn, args0, k1=4, reps=3, target=2.0):
    def chain_t(iters):
        @jax.jit
        def chain(a):
            def body(carry, _):
                return step_fn(carry), None
            c, _ = lax.scan(body, a, None, length=iters)
            return jax.tree_util.tree_reduce(
                lambda s, t: s + jnp.sum(t[..., :1].astype(jnp.float32)),
                c, 0.0)

        float(chain(args0))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(args0))
            best = min(best, time.perf_counter() - t0)
        return best

    t_probe = chain_t(k1)
    per0 = max(t_probe / k1, 1e-4)
    k_long = max(k1, int(target / per0))
    k_short = max(1, k_long // 5)
    t1 = chain_t(k_short)
    t2 = chain_t(k_long)
    return (t2 - t1) / (k_long - k_short)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    args = ap.parse_args()
    b = args.batch
    rng = np.random.default_rng(0)
    params = pr.init_params(jax.random.key(0))
    x = jnp.asarray(rng.normal(size=(b, 224, 224, 3)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 1000, (b,)), jnp.int32)

    def step_for(fwd):
        def step(carry):
            params, xx = carry

            def loss_fn(p):
                lp = jax.nn.log_softmax(fwd(p, xx))
                return -jnp.mean(jnp.take_along_axis(lp, labels[:, None],
                                                     1))

            l, g = jax.value_and_grad(loss_fn)(params)
            params = jax.tree_util.tree_map(
                lambda p, gg: p - 1e-6 * gg.astype(p.dtype), params, g)
            return (params, xx + (l * 1e-30).astype(xx.dtype))
        return step

    variants = [
        ("baseline", make_forward_remat("NHWC", "onepass", ())),
        ("remat_all", make_forward_remat("NHWC", "onepass", (0, 1, 2, 3))),
        ("remat_early", make_forward_remat("NHWC", "onepass", (0, 1))),
        ("nchw", make_forward_remat("NCHW", "onepass", ())),
    ]
    for name, fwd in variants:
        per = slope_time(step_for(fwd), (params, x))
        ips = b / per
        mfu = ips * pr.TRAIN_FLOPS_PER_IMG / V5E_PEAK_BF16
        print(json.dumps({"variant": name, "step_ms": round(per * 1e3, 2),
                          "img_per_sec": round(ips, 1),
                          "mfu": round(mfu, 4)}), flush=True)


if __name__ == "__main__":
    main()


def make_forward_bnlite(layout="NHWC"):
    """One-pass BN with bf16 stat reductions (f32 accumulate via dot...
    actually jnp.mean on bf16 inputs with f32 dtype arg): halves the
    stat-pass HBM traffic at s0-sized tensors."""
    dn = ("NHWC", "HWIO", "NHWC")

    def conv(x, w, stride):
        return lax.conv_general_dilated(
            x, w.astype(x.dtype), (stride, stride), "SAME",
            dimension_numbers=dn)

    def bn(x, g, b):
        mean = jnp.mean(x, axis=(0, 1, 2), dtype=jnp.float32)
        var = jnp.mean(jnp.square(x), axis=(0, 1, 2),
                       dtype=jnp.float32) - jnp.square(mean)
        inv = lax.rsqrt(var + 1e-5) * g
        shape = [1, 1, 1, -1]
        return (x.astype(jnp.float32) * inv.reshape(shape)
                + (b - mean * inv).reshape(shape)).astype(x.dtype)

    def cbr(x, pp, stride, relu=True):
        y = bn(conv(x, pp["w"], stride), pp["g"], pp["b"])
        return jax.nn.relu(y) if relu else y

    def forward(params, x):
        y = cbr(x, params["stem"], 2)
        y = lax.reduce_window(y, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
        for si, (f, blocks, stride) in enumerate(pr.STAGES):
            for bi in range(blocks):
                blk = params[f"s{si}b{bi}"]
                s = stride if bi == 0 else 1
                h = cbr(y, blk["c1"], s)
                h = cbr(h, blk["c2"], 1)
                h = cbr(h, blk["c3"], 1, relu=False)
                if bi == 0:
                    y = cbr(y, blk["proj"], s, relu=False)
                y = jax.nn.relu(y + h)
        y = jnp.mean(y.astype(jnp.float32), axis=(1, 2))
        return y @ params["fc"]["w"] + params["fc"]["b"]

    return forward
