"""GravesLSTM char-RNN perf probe: tokens/s + MFU + roofline across
batch sizes (VERDICT round-2 item 6: the recurrent path needs a
fraction-of-peak number and a probe-backed statement of where it sits).

Model = zoo TextGenerationLSTM (2x LSTM h=256 + softmax head, vocab 77,
T=100, one-hot inputs) trained via fitMultiBatch K-step scan launches —
the BASELINE.json configs[2] measurement path.

Run: python tools/probe_lstm.py [--batches 64,256,1024]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

V5E_PEAK_BF16 = 197e12
HBM_GBPS = 819e9


def train_flops_per_token(vocab=77, h=256):
    """fwd: L1 8h(vocab+h) + L2 8h(h+h) + head 2hv; train ~= 3x fwd."""
    fwd = 8 * h * (vocab + h) + 8 * h * (h + h) + 2 * h * vocab
    return 3 * fwd


def measure(batch, k=8, vocab=77, seq=100, hidden=256):
    import jax

    from deeplearning4j_tpu.models.zoo import TextGenerationLSTM

    net = TextGenerationLSTM(vocabSize=vocab, hidden=hidden,
                             seqLength=seq).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, (k, batch, seq + 1))
    X_k = np.stack([np.eye(vocab, dtype=np.float32)[ids[i, :, :-1]]
                    .transpose(0, 2, 1) for i in range(k)])
    y_k = np.stack([np.eye(vocab, dtype=np.float32)[ids[i, :, 1:]]
                    .transpose(0, 2, 1) for i in range(k)])
    X_k = jax.device_put(jax.numpy.asarray(X_k))
    y_k = jax.device_put(jax.numpy.asarray(y_k))
    float(net.fitMultiBatch(X_k, y_k)[-1])
    float(net.fitMultiBatch(X_k, y_k)[-1])
    best = float("inf")
    for _ in range(4):
        t0 = time.perf_counter()
        float(net.fitMultiBatch(X_k, y_k)[-1])
        best = min(best, (time.perf_counter() - t0) / k)
    toks = batch * seq / best
    mfu = toks * train_flops_per_token(vocab, hidden) / V5E_PEAK_BF16
    # latency roofline: fwd runs 2 layers x T sequential scan steps, bwd
    # re-runs them reversed -> >= 4*T dependent steps per optimizer step
    steps = 4 * seq
    return {"batch": batch, "tokens_per_sec": round(toks, 1),
            "step_ms": round(best * 1e3, 3), "mfu": round(mfu, 5),
            "us_per_sequential_step": round(best / steps * 1e6, 2)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="64,256,1024")
    ap.add_argument("--ksteps", type=int, default=8)
    args = ap.parse_args()
    for b in (int(x) for x in args.batches.split(",")):
        print(json.dumps(measure(b, k=args.ksteps)), flush=True)


if __name__ == "__main__":
    main()
