"""SGNS training-step decomposition probe (r5, VERDICT item 1).

The r4 bench note: the 10M-word epoch = ~4.4 s device pair-gen +
~8.9 s training scan, updates at ~4.5M pairs/s against a 125M rows/s
sorted-scatter primitive. This probe isolates the step's levers on the
real chip with slope timing:

  A  current step (gathers + analytic grads + 2 sorted dup scatters)
  B  no-sort (raw duplicate scatter — is the argsort paying for itself?)
  C  sort + cumsum segment-sum -> UNIQUE-row scatter (dedup before
     scatter; Zipf batches have heavy duplication)
  D  batch-width sweep of A and C (8k/32k/128k rows per step)
  E  the gather+matmul math alone (no scatter) — the non-scatter floor

Run: python tools/probe_w2v_step.py   (on the axon TPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V, D, K_NEG = 100_000, 128, 5
LR = 0.025


def slope(make_chain, k1=40, reps=3):
    def chain_t(iters):
        fn = make_chain(iters)
        fn()  # compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chain_t(k1)
    t2 = chain_t(5 * k1)
    return (t2 - t1) / (4 * k1)


def make_batches(bsz, rng):
    probs = (np.arange(1, V + 1) ** -1.05)
    probs /= probs.sum()
    neg_probs = (np.arange(1, V + 1) ** -0.75)
    neg_probs /= neg_probs.sum()
    cent = rng.choice(V, size=bsz, p=probs).astype(np.int32)
    ctx = rng.choice(V, size=bsz, p=probs).astype(np.int32)
    negs = rng.choice(V, size=(bsz, K_NEG), p=neg_probs).astype(np.int32)
    w = np.ones(bsz, np.float32)
    return (jnp.asarray(cent), jnp.asarray(ctx), jnp.asarray(negs),
            jnp.asarray(w))


def grads(syn0, syn1, cent, ctx, negs, w):
    c = syn0[cent]
    pos = syn1[ctx]
    neg = syn1[negs]
    pos_s = jnp.sum(c * pos, axis=-1)
    neg_s = jnp.einsum("bd,bkd->bk", c, neg)
    dpos = -(1.0 - jax.nn.sigmoid(pos_s)) * w
    dneg = jax.nn.sigmoid(neg_s) * w[:, None]
    gc = dpos[:, None] * pos + jnp.einsum("bk,bkd->bd", dneg, neg)
    ids1 = jnp.concatenate([ctx, negs.reshape(-1)])
    u1 = jnp.concatenate([
        dpos[:, None] * c,
        (dneg[..., None] * c[:, None, :]).reshape(-1, D)])
    return gc, ids1, u1


def apply_sorted(table, ids, upd):
    o = jnp.argsort(ids)
    return table.at[ids[o]].add(-LR * upd[o], indices_are_sorted=True)


def apply_unsorted(table, ids, upd):
    return table.at[ids].add(-LR * upd)


def apply_unique(table, ids, upd):
    """Sort, segment-sum duplicate rows, scatter UNIQUE sorted rows."""
    o = jnp.argsort(ids)
    sid = ids[o]
    u = upd[o]
    n = sid.shape[0]
    is_first = jnp.concatenate([jnp.ones((1,), bool),
                                sid[1:] != sid[:-1]])
    seg_id = jnp.cumsum(is_first.astype(jnp.int32)) - 1   # sorted
    seg = jax.ops.segment_sum(u, seg_id, num_segments=n,
                              indices_are_sorted=True)
    firsts = jnp.nonzero(is_first, size=n, fill_value=n - 1)[0]
    n_seg = seg_id[-1] + 1
    dest = jnp.where(jnp.arange(n) < n_seg, sid[firsts], V)
    return table.at[dest].add(-LR * seg, mode="drop",
                              unique_indices=True,
                              indices_are_sorted=True)


def step_variant(apply1, apply0):
    def step(syn0, syn1, cent, ctx, negs, w):
        gc, ids1, u1 = grads(syn0, syn1, cent, ctx, negs, w)
        syn0 = apply0(syn0, cent, gc)
        syn1 = apply1(syn1, ids1, u1)
        return syn0, syn1

    return step


def math_only(syn0, syn1, cent, ctx, negs, w):
    gc, ids1, u1 = grads(syn0, syn1, cent, ctx, negs, w)
    return syn0 - 1e-9 * jnp.sum(gc), syn1 - 1e-9 * jnp.sum(u1)


def time_step(step, bsz, rng):
    batch = make_batches(bsz, rng)
    syn0 = jnp.asarray(rng.normal(size=(V, D)) * 0.01, jnp.float32)
    syn1 = jnp.zeros((V, D), jnp.float32)

    def make_chain(iters):
        @jax.jit
        def chain(s0, s1):
            def body(carry, _):
                a, b = carry
                return step(a, b, *batch), None
            (a, b), _ = lax.scan(body, (s0, s1), None, length=iters)
            return jnp.sum(a[0, :1]) + jnp.sum(b[0, :1])

        def run():
            return float(chain(syn0, syn1))

        return run

    return slope(make_chain)


def time_step_proddraw(bsz, rng, table_size=10_000_000,
                       key_impl="rbg", draw_only=False):
    """Replica of the production scan body: negatives drawn ON DEVICE
    per step (fold_in + randint + unigram-table gather), then the A
    step. draw_only=True times just the draw+gather."""
    cent, ctx, _negs, w = make_batches(bsz, rng)
    table = jnp.asarray(
        rng.integers(0, V, table_size).astype(np.int32))
    syn0 = jnp.asarray(rng.normal(size=(V, D)) * 0.01, jnp.float32)
    syn1 = jnp.zeros((V, D), jnp.float32)
    key = jax.random.key(7, impl=key_impl)
    base_step = step_variant(apply_sorted, apply_sorted)

    def make_chain(iters):
        @jax.jit
        def chain(s0, s1):
            def body(carry, _):
                a, b, i = carry
                draws = jax.random.randint(
                    jax.random.fold_in(key, i),
                    (bsz, K_NEG), 0, table_size)
                negs = table[draws]
                if draw_only:
                    a = a + 1e-12 * jnp.sum(negs).astype(jnp.float32)
                else:
                    a, b = base_step(a, b, cent, ctx, negs, w)
                return (a, b, i + 1), None
            (a, b, _), _ = lax.scan(body, (s0, s1, jnp.int32(0)),
                                    None, length=iters)
            return jnp.sum(a[0, :1]) + jnp.sum(b[0, :1])

        def run():
            return float(chain(syn0, syn1))

        return run

    return slope(make_chain)


def main():
    rng = np.random.default_rng(0)
    print(json.dumps({"V": V, "D": D, "k_neg": K_NEG,
                      "device": str(jax.devices()[0])}), flush=True)
    rows_per_pair = 1 + 1 + K_NEG  # cent + ctx + negs

    for bsz in (8192,):
        for name, kw in (
                ("F_prod_replica_rbg", {}),
                ("F_prod_replica_threefry", {"key_impl": "threefry2x32"}),
                ("G_draw_gather_only_rbg", {"draw_only": True}),
                ("H_prod_small_table", {"table_size": 1_000_000}),
        ):
            per = time_step_proddraw(bsz, rng, **kw)
            print(json.dumps({
                "variant": name, "bsz": bsz,
                "ms_per_step": round(per * 1e3, 3),
                "pairs_per_s_M": round(bsz / per / 1e6, 2),
            }), flush=True)

    for bsz in (8192, 32768, 131072):
        batch_dup = make_batches(bsz, rng)
        ids1 = np.concatenate([np.asarray(batch_dup[1]),
                               np.asarray(batch_dup[2]).ravel()])
        uniq = len(np.unique(ids1))
        variants = {
            "A_sorted_dup": step_variant(apply_sorted, apply_sorted),
            "B_unsorted": step_variant(apply_unsorted, apply_unsorted),
            "C_unique_seg": step_variant(apply_unique, apply_unique),
            "E_math_only": math_only,
        }
        for name, st in variants.items():
            per = time_step(st, bsz, rng)
            print(json.dumps({
                "variant": name, "bsz": bsz,
                "uniq_frac_syn1": round(uniq / len(ids1), 3),
                "ms_per_step": round(per * 1e3, 3),
                "pairs_per_s_M": round(bsz / per / 1e6, 2),
                "rows_per_s_M": round(bsz * rows_per_pair / per / 1e6, 1),
            }), flush=True)


if __name__ == "__main__":
    main()
