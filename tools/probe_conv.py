"""Per-shape conv throughput probe for the ResNet-50 MFU diagnosis.

Measures fwd and fwd+bwd TF/s for every distinct conv shape in ResNet-50
(224x224), in both NCHW (the DL4J-parity layout the framework uses) and
NHWC (TPU-native: channels in the 128-lane minor dim), bf16, plus pooled
full-model probes. This is the evidence base for the round-2/3 claim
about which shapes cap ResNet MFU on v5e — VERDICT round 2 "What's weak"
item 1 demanded it be committed.

Run on the real chip:  python tools/probe_conv.py [--batch 256]
Writes tools/probe_conv_results.json and prints a table.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V5E_PEAK_BF16 = 197e12

# Every distinct conv in ResNet-50 at 224x224:
# (name, Cin, Cout, k, stride, Hin) — Hin is the INPUT spatial size.
RESNET50_CONVS = [
    ("stem7x7s2", 3, 64, 7, 2, 224),
    # stage 1 @56 (input 56 after 3x3/s2 maxpool of the 112 stem output)
    ("s1_1x1a", 64, 64, 1, 1, 56),
    ("s1_3x3", 64, 64, 3, 1, 56),
    ("s1_1x1b", 64, 256, 1, 1, 56),
    ("s1_proj", 64, 256, 1, 1, 56),
    ("s1_1x1a_in256", 256, 64, 1, 1, 56),
    # stage 2 @28
    ("s2_1x1a_s2", 256, 128, 1, 2, 56),
    ("s2_proj_s2", 256, 512, 1, 2, 56),
    ("s2_3x3", 128, 128, 3, 1, 28),
    ("s2_1x1b", 128, 512, 1, 1, 28),
    ("s2_1x1a", 512, 128, 1, 1, 28),
    # stage 3 @14
    ("s3_1x1a_s2", 512, 256, 1, 2, 28),
    ("s3_proj_s2", 512, 1024, 1, 2, 28),
    ("s3_3x3", 256, 256, 3, 1, 14),
    ("s3_1x1b", 256, 1024, 1, 1, 14),
    ("s3_1x1a", 1024, 256, 1, 1, 14),
    # stage 4 @7
    ("s4_1x1a_s2", 1024, 512, 1, 2, 14),
    ("s4_proj_s2", 1024, 2048, 1, 2, 14),
    ("s4_3x3", 512, 512, 3, 1, 7),
    ("s4_1x1b", 512, 2048, 1, 1, 7),
    ("s4_1x1a", 2048, 512, 1, 1, 7),
]


def conv_flops(batch, cin, cout, k, stride, hin):
    hout = (hin + stride - 1) // stride
    return 2 * batch * hout * hout * cin * cout * k * k


def _iters_for(flops):
    """Iteration count putting ~0.5 s of work in ONE launch, so the axon
    tunnel's 25-100 ms per-dispatch RTT is amortized away (assume ~5%
    efficiency as the floor; clamp for compile time)."""
    est = flops / (197e12 * 0.05)
    return int(min(512, max(48, 0.5 / max(est, 1e-9))))


def _time(fn, iters, *args):
    """Time an iterated-loop executable whose scalar result forces a full
    device sync via the host read. (block_until_ready is NOT a reliable
    sync under the axon tunnel — it can resolve before the remote compute
    finishes, which inflated an earlier version of this probe ~30x; the
    scalar float() readback is how every bench in this repo syncs.)"""
    float(fn(*args))  # compile
    float(fn(*args))  # warm
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def probe_shape(name, cin, cout, k, stride, hin, batch, layout):
    rng = np.random.default_rng(0)
    if layout == "NCHW":
        dn = ("NCHW", "OIHW", "NCHW")
        x = jnp.asarray(rng.normal(size=(batch, cin, hin, hin)),
                        jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(cout, cin, k, k)) * 0.05,
                        jnp.bfloat16)
    else:
        dn = ("NHWC", "HWIO", "NHWC")
        x = jnp.asarray(rng.normal(size=(batch, hin, hin, cin)),
                        jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(k, k, cin, cout)) * 0.05,
                        jnp.bfloat16)
    pad = "SAME"

    def conv(x, w):
        return lax.conv_general_dilated(
            x, w, (stride, stride), pad, dimension_numbers=dn)

    fl = conv_flops(batch, cin, cout, k, stride, hin)
    it_f = _iters_for(fl)
    it_fb = _iters_for(3 * fl)

    @jax.jit
    def fwd(x, w):
        # serialized iteration: each conv's weights depend on the previous
        # iteration's output sum, so XLA cannot overlap or elide the chain
        def body(i, acc):
            y = conv(x, w + (acc * 1e-30).astype(w.dtype))
            return jnp.sum(y.astype(jnp.float32)) * 1e-30
        return lax.fori_loop(0, it_f, body, jnp.float32(0.0))

    @jax.jit
    def fwdbwd(x, w):
        def loss(x, w):
            return jnp.sum(conv(x, w).astype(jnp.float32))

        def body(i, acc):
            gx, gw = jax.grad(loss, argnums=(0, 1))(
                x, w + (acc * 1e-30).astype(w.dtype))
            return (gx.astype(jnp.float32).sum()
                    + gw.astype(jnp.float32).sum()) * 1e-30
        return lax.fori_loop(0, it_fb, body, jnp.float32(0.0))

    t_f = _time(fwd, it_f, x, w)
    t_fb = _time(fwdbwd, it_fb, x, w)
    return {
        "name": name, "layout": layout,
        "cin": cin, "cout": cout, "k": k, "stride": stride, "hin": hin,
        "fwd_tflops": round(fl / t_f / 1e12, 1),
        "train_tflops": round(3 * fl / t_fb / 1e12, 1),
        "fwd_pct_peak": round(100 * fl / t_f / V5E_PEAK_BF16, 1),
        "train_pct_peak": round(100 * 3 * fl / t_fb / V5E_PEAK_BF16, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--layouts", default="NCHW,NHWC")
    args = ap.parse_args()

    print(f"device: {jax.devices()[0]}, batch={args.batch}", flush=True)
    results = []
    for layout in args.layouts.split(","):
        for spec in RESNET50_CONVS:
            r = probe_shape(*spec, args.batch, layout)
            results.append(r)
            print(f"{r['name']:>14} {layout}  fwd {r['fwd_tflops']:>6.1f} "
                  f"TF/s ({r['fwd_pct_peak']:>4.1f}%)  train "
                  f"{r['train_tflops']:>6.1f} TF/s "
                  f"({r['train_pct_peak']:>4.1f}%)", flush=True)

    # weighted whole-model estimate per layout: sum(flops)/sum(time)
    out = {"batch": args.batch, "device": str(jax.devices()[0]),
           "shapes": results}
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "probe_conv_results.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
