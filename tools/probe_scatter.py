"""TPU scatter-add primitives for the Word2Vec update path (r4).

VERDICT r3 item 2: attack the 374.8k words/s scatter bound with a
different algorithm. This probe measures the primitive space on a
realistic workload (V=100k vocab, D=128, Zipf-ish unigram^0.75 ids,
R update rows per step):

  scatter_rand     - .at[ids].add(upd), random duplicate ids (current)
  scatter_sorted   - same ids sorted, indices_are_sorted=True
  scatter_unique   - R DISTINCT sorted ids: can XLA parallelize when it
                     does not have to serialize duplicate rows?
  sort_machinery   - argsort+gather+cumsum+flags alone (compaction cost)
  hot_matmul       - one-hot [R,H] @ upd MXU accumulation into a dense
                     top-H slab (no scatter at all; H=4096)

Slope-timed (two-span) to cancel the axon-tunnel RTT.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

V, D, R, H = 100_000, 128, 28_672, 4096


def slope(step_fn, x0, k1=100, reps=3):
    def chain_t(iters):
        @jax.jit
        def chain(a):
            def body(carry, _):
                return step_fn(carry), None
            c, _ = lax.scan(body, a, None, length=iters)
            return jnp.sum(c[..., :1].astype(jnp.float32))

        float(chain(x0))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(x0))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chain_t(k1)
    t2 = chain_t(5 * k1)
    return (t2 - t1) / (4 * k1)


rng = np.random.default_rng(0)
probs = (np.arange(1, V + 1) ** -0.75)
probs /= probs.sum()
ids_np = rng.choice(V, size=R, p=probs).astype(np.int32)
frac_hot = float((ids_np < H).mean())
upd = jnp.asarray(rng.normal(size=(R, D)) * 1e-4, jnp.float32)
table = jnp.zeros((V, D), jnp.float32)
ids = jnp.asarray(ids_np)
ids_sorted = jnp.asarray(np.sort(ids_np))
ids_unique = jnp.asarray(
    np.sort(rng.choice(V, size=R, replace=False)).astype(np.int32))

out = {"V": V, "D": D, "R": R, "H": H, "frac_hot": round(frac_hot, 3)}
print(json.dumps(out), flush=True)


def report(name, per):
    print(json.dumps({
        "variant": name, "ms": round(per * 1e3, 3),
        "rows_per_s_M": round(R / per / 1e6, 1),
        "bytes_gbps": round(R * D * 4 * 3 / per / 1e9, 1)}), flush=True)


report("scatter_rand", slope(
    lambda t: t.at[ids].add(upd), table))
report("scatter_sorted", slope(
    lambda t: t.at[ids_sorted].add(upd, indices_are_sorted=True), table))
report("scatter_unique", slope(
    lambda t: t.at[ids_unique].add(upd, indices_are_sorted=True,
                                   unique_indices=True), table))


def machinery(t):
    order = jnp.argsort(ids)
    ids_s = ids[order]
    upd_s = upd[order]
    csum = jnp.cumsum(upd_s, axis=0)
    last = jnp.concatenate([ids_s[1:] != ids_s[:-1],
                            jnp.ones((1,), bool)])
    return t + (jnp.sum(csum[-1] * last[-1]) * 1e-30)


report("sort_machinery", slope(machinery, table))


def hot_matmul(t):
    onehot = (ids[:, None] == jnp.arange(H)[None, :]).astype(jnp.bfloat16)
    slab = lax.dot_general(onehot, upd.astype(jnp.bfloat16),
                           (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.float32)
    return t.at[:H].add(slab)


report("hot_matmul", slope(hot_matmul, table))
