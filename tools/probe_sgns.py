"""Full SGNS step throughput vs batch size (r4).

probe_scatter r4: raw scatter-add runs at 78M rows/s (sorted 125M) —
4x the r3 claim (RTT-polluted). The measured word2vec epoch (~375k
words/s ~ 2.6M pairs/s ~ 18M rows/s) is therefore NOT scatter-bound.
This probe times one fused SGNS step (gathers + loss + grads + scatter
updates, donated) at varying batch size, plus a sorted-custom-backward
variant, to find the real ceiling.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from deeplearning4j_tpu.nlp.word2vec import _sgns_loss

V, D, K = 100_000, 128, 5
LR = 0.025


def slope(step_fn, carry0, k1=60, reps=3):
    def chain_t(iters):
        @jax.jit
        def chain(c):
            def body(carry, i):
                return step_fn(carry, i), None
            c2, _ = lax.scan(body, c, jnp.arange(iters))
            return jnp.sum(c2[0][0, :1])

        float(chain(carry0))
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(chain(carry0))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = chain_t(k1)
    t2 = chain_t(5 * k1)
    return (t2 - t1) / (4 * k1)


rng = np.random.default_rng(0)
probs = (np.arange(1, V + 1) ** -0.75)
probs /= probs.sum()
table_np = rng.choice(V, size=1_000_000, p=probs).astype(np.int32)
table_dev = jnp.asarray(table_np)


def bench(bsz, variant):
    centers = jnp.asarray(rng.choice(V, size=bsz, p=probs).astype(np.int32))
    contexts = jnp.asarray(rng.choice(V, size=bsz, p=probs).astype(np.int32))
    w = jnp.ones((bsz,), jnp.float32)
    syn0 = jnp.asarray(rng.normal(size=(V, D)) * 0.01, jnp.float32)
    syn1 = jnp.zeros((V, D), jnp.float32)
    key = jax.random.key(0)

    if variant == "grad":
        def step(carry, i):
            syn0, syn1 = carry
            negs = table_dev[jax.random.randint(
                jax.random.fold_in(key, i), (bsz, K), 0, table_dev.shape[0])]
            loss, (g0, g1) = jax.value_and_grad(
                _sgns_loss, argnums=(0, 1))(syn0, syn1, centers, contexts,
                                            negs, w)
            return (syn0 - LR * g0, syn1 - LR * g1)

    else:  # sorted custom backward: analytic grads, one sorted scatter/table
        def step(carry, i):
            syn0, syn1 = carry
            negs = table_dev[jax.random.randint(
                jax.random.fold_in(key, i), (bsz, K), 0,
                table_dev.shape[0])]
            c = syn0[centers]
            pos = syn1[contexts]
            neg = syn1[negs]
            pos_s = jnp.sum(c * pos, axis=-1)
            neg_s = jnp.einsum("bd,bkd->bk", c, neg)
            # d/ds softplus(-s) = -(1-sigmoid(s)); softplus(s) = sigmoid(s)
            dpos = -(1.0 - jax.nn.sigmoid(pos_s)) * w          # [B]
            dneg = jax.nn.sigmoid(neg_s) * w[:, None]          # [B,K]
            gc = dpos[:, None] * pos + jnp.einsum("bk,bkd->bd", dneg, neg)
            gpos = dpos[:, None] * c
            gneg = dneg[..., None] * c[:, None, :]
            ids0 = centers
            o0 = jnp.argsort(ids0)
            syn0 = syn0.at[ids0[o0]].add(-LR * gc[o0],
                                         indices_are_sorted=True)
            ids1 = jnp.concatenate([contexts, negs.reshape(-1)])
            u1 = jnp.concatenate([gpos, gneg.reshape(-1, D)])
            o1 = jnp.argsort(ids1)
            syn1 = syn1.at[ids1[o1]].add(-LR * u1[o1],
                                        indices_are_sorted=True)
            return (syn0, syn1)

    per = slope(step, (syn0, syn1))
    pairs_per_s = bsz / per
    print(json.dumps({"variant": variant, "bsz": bsz,
                      "step_us": round(per * 1e6, 1),
                      "Mpairs_per_s": round(pairs_per_s / 1e6, 2)}),
          flush=True)


for bsz in (512, 2048, 8192, 32768):
    bench(bsz, "grad")
for bsz in (2048, 8192, 32768):
    bench(bsz, "sorted")


def host_numpy_reference(n_pairs=200_000):
    """Vectorized numpy SGNS on this host — the CPU reference point
    VERDICT r3 item 2 asks for (how fast would the reference's
    CPU-side path go HERE). Batched like the device path (bsz 8192)."""
    rng_l = np.random.default_rng(3)
    syn0 = rng_l.normal(size=(V, D)).astype(np.float32) * 0.01
    syn1 = np.zeros((V, D), np.float32)
    bsz = 8192
    cents = rng_l.choice(V, size=n_pairs, p=probs).astype(np.int32)
    ctxs = rng_l.choice(V, size=n_pairs, p=probs).astype(np.int32)
    t0 = time.perf_counter()
    for lo in range(0, n_pairs, bsz):
        c_i = cents[lo:lo + bsz]
        x_i = ctxs[lo:lo + bsz]
        negs = table_np[rng_l.integers(0, len(table_np),
                                       (len(c_i), K))]
        c = syn0[c_i]
        pos = syn1[x_i]
        neg = syn1[negs]
        pos_s = np.sum(c * pos, axis=-1)
        neg_s = np.einsum("bd,bkd->bk", c, neg)
        sig_p = 1.0 / (1.0 + np.exp(pos_s))
        sig_n = 1.0 / (1.0 + np.exp(-neg_s))
        gc = -sig_p[:, None] * pos + np.einsum("bk,bkd->bd", sig_n, neg)
        np.add.at(syn0, c_i, -LR * gc)
        np.add.at(syn1, x_i, LR * sig_p[:, None] * c)
        np.add.at(syn1, negs.reshape(-1),
                  (-LR * sig_n[..., None] * c[:, None, :]).reshape(-1, D))
    dt = time.perf_counter() - t0
    print(json.dumps({"variant": "host_numpy", "bsz": bsz,
                      "Mpairs_per_s": round(n_pairs / dt / 1e6, 3),
                      "words_per_s_at_3.8pairs":
                          round(n_pairs / dt / 3.8, 1)}), flush=True)


if "--host" in sys.argv or True:
    host_numpy_reference()
